"""Data distribution: shard map, splits/merges, and team rebalancing.

Ref parity: fdbserver/DataDistribution.actor.cpp + DDTracker/DDQueue —
the reference divides the keyspace into contiguous shards, tracks each
shard's size via storage-server byte samples, splits shards that grow
past the split threshold, merges runs of small shards, and enqueues
RelocateShard moves so every storage team carries a fair share.

Ours is the same control loop, host-side (this is metadata work — it
does not belong on the TPU): a ``ShardMap`` of boundary → team, byte
accounting fed by the commit proxy, and a ``rebalance()`` step the
cluster pumps periodically (simulation pumps it deterministically).
Replication: a shard's team is a list of storage ids; moves copy the
shard's data to the destination before flipping the map, so reads at
old versions keep working (the reference's fetchKeys + TSS-free path).
"""

import bisect

from foundationdb_tpu.utils.trace import TraceEvent


class ShardMap:
    """Contiguous partition of the keyspace: boundaries[i] owns
    [boundaries[i], boundaries[i+1]). boundaries[0] is always b"".

    Ref: keyServers / shardBoundaries in the system keyspace.
    """

    def __init__(self, teams=None):
        self.boundaries = [b""]
        self.teams = [list(teams[0]) if teams else [0]]

    def team_for(self, key):
        return self.teams[bisect.bisect_right(self.boundaries, key) - 1]

    def shard_index(self, key):
        return bisect.bisect_right(self.boundaries, key) - 1

    def shard_range(self, i):
        end = self.boundaries[i + 1] if i + 1 < len(self.boundaries) else None
        return self.boundaries[i], end

    def shards_overlapping(self, begin, end):
        """Indices of shards intersecting [begin, end)."""
        i = self.shard_index(begin)
        out = []
        while i < len(self.boundaries):
            b = self.boundaries[i]
            if end is not None and b >= end:
                break
            out.append(i)
            i += 1
        return out

    def split(self, i, at):
        b, e = self.shard_range(i)
        if not (b < at and (e is None or at < e)):
            raise ValueError(f"split point {at!r} outside shard [{b!r}, {e!r})")
        self.boundaries.insert(i + 1, at)
        self.teams.insert(i + 1, list(self.teams[i]))

    def merge(self, i):
        """Merge shard i+1 into shard i (teams must match)."""
        if i + 1 >= len(self.boundaries):
            raise ValueError("no right neighbor to merge")
        if self.teams[i] != self.teams[i + 1]:
            raise ValueError("cannot merge shards on different teams")
        del self.boundaries[i + 1]
        del self.teams[i + 1]

    def assign(self, i, team):
        self.teams[i] = list(team)

    def __len__(self):
        return len(self.boundaries)


class DataDistributor:
    """The DD control loop over a cluster's storage servers.

    The commit proxy calls ``note_write(key, nbytes)`` per mutation
    (the analog of storage byte sampling); ``rebalance()`` runs one
    round of split / merge / move decisions and returns the moves it
    performed, each as (shard_range, old_team, new_team).
    """

    def __init__(self, storages, shard_map=None, replication=1,
                 max_shard_bytes=250_000, min_shard_bytes=10_000):
        self.storages = storages
        self.replication = min(replication, len(storages))
        self.map = shard_map or ShardMap(
            teams=[list(range(self.replication))]
        )
        self.max_shard_bytes = max_shard_bytes
        self.min_shard_bytes = min_shard_bytes
        self._sizes = [0] * len(self.map)
        # per-shard hottest-prefix sample for split points
        self._last_key = [None] * len(self.map)

    def note_write(self, key, nbytes):
        i = self.map.shard_index(key)
        self._sizes[i] += nbytes
        self._last_key[i] = key

    def note_clear_range(self, begin, end):
        for i in self.map.shards_overlapping(begin, end):
            self._sizes[i] = max(0, self._sizes[i] // 2)

    def team_bytes(self):
        out = [0] * len(self.storages)
        for size, team in zip(self._sizes, self.map.teams):
            for s in team:
                out[s] += size
        return out

    def rebalance(self):
        moves = []
        self._split_large()
        self._merge_small()
        moves.extend(self._move_for_balance())
        return moves

    # ── splits (ref: shardSplitter) ──
    def _split_large(self):
        i = 0
        while i < len(self.map):
            if self._sizes[i] > self.max_shard_bytes:
                at = self._split_point(i)
                if at is not None:
                    self.map.split(i, at)
                    half = self._sizes[i] // 2
                    self._sizes[i] -= half
                    self._sizes.insert(i + 1, half)
                    self._last_key.insert(i + 1, self._last_key[i])
                    TraceEvent("DDShardSplit").detail(
                        index=i, at=at, bytes=half * 2).log()
                    i += 1
            i += 1

    def _split_point(self, i):
        """Median key of the shard from the owning storage's live data."""
        b, e = self.map.shard_range(i)
        team = self.map.teams[i]
        storage = self.storages[team[0]]
        keys = [k for k, _ in storage.read_range(
            b, e, storage.version, limit=1001)]
        if len(keys) < 2:
            return None
        at = keys[len(keys) // 2]
        return at if b < at else None

    # ── merges (ref: shardMerger) ──
    def _merge_small(self):
        i = 0
        while i + 1 < len(self.map):
            if (
                self._sizes[i] + self._sizes[i + 1] < self.min_shard_bytes
                and self.map.teams[i] == self.map.teams[i + 1]
            ):
                self.map.merge(i)
                self._sizes[i] += self._sizes.pop(i + 1)
                self._last_key.pop(i + 1)
            else:
                i += 1

    # ── moves (ref: BgDDMountainChopper / ValleyFiller) ──
    def _move_for_balance(self):
        if len(self.storages) < 2:
            return []
        moves = []
        for _ in range(2):  # bounded moves per round, like DD's queue
            load = self.team_bytes()
            hot = max(range(len(load)), key=load.__getitem__)
            cold = min(range(len(load)), key=load.__getitem__)
            diff = load[hot] - load[cold]
            if diff < self.max_shard_bytes:
                break
            # biggest shard on `hot` but not `cold` that strictly improves
            # balance (size < diff, else the move just flips the skew)
            cands = [
                i for i, team in enumerate(self.map.teams)
                if hot in team and cold not in team and self._sizes[i] < diff
            ]
            if not cands:
                break
            i = max(cands, key=self._sizes.__getitem__)
            old_team = list(self.map.teams[i])
            new_team = [cold if s == hot else s for s in old_team]
            self._relocate(i, old_team, new_team)
            moves.append((self.map.shard_range(i), old_team, new_team))
        return moves

    def _relocate(self, i, old_team, new_team):
        """Copy shard data to joining storages, then flip the map entry
        (ref: fetchKeys then the keyServers commit)."""
        b, e = self.map.shard_range(i)
        src = self.storages[old_team[0]]
        joining = [s for s in new_team if s not in old_team]
        for sid in joining:
            dst = self.storages[sid]
            rows = src.read_range(b, e, src.version, limit=None)
            dst.ingest_shard(b, e, src.version, rows)
        self.map.assign(i, new_team)
        TraceEvent("DDRelocateShard").detail(
            begin=b, end=e, old=old_team, new=new_team).log()
