"""Native runtime pieces: the C++ ConflictSet behind resolver_backend="native".

Builds conflict_set.cpp with g++ on first use (cached as a .so beside the
source; rebuilt when the source is newer) and binds it with ctypes — no
pybind11 dependency. The batch ABI moves whole commit batches across the
FFI boundary in packed numpy arrays, mirroring how the TPU path packs
batches into device arrays (resolver/packing.py).

Ref parity: fdbserver/SkipList.cpp ConflictSet (role), bindings/c (the
C-ABI shape of the reference's native surface).
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

from foundationdb_tpu.core.status import COMMITTED, CONFLICT, TOO_OLD
from foundationdb_tpu.utils import lockdep

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "conflict_set.cpp")
_SO = os.path.join(_HERE, "libconflictset.so")
_lock = lockdep.lock("native._lock")
_lib = None


class NativeBuildError(RuntimeError):
    pass


def _compile(src, so, extra_flags=()):
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
        *extra_flags, "-o", so, src,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except FileNotFoundError as e:
        raise NativeBuildError("g++ not available") from e
    except subprocess.CalledProcessError as e:
        raise NativeBuildError(f"native build failed:\n{e.stderr}") from e


def _build():
    _compile(_SRC, _SO)


def load_library():
    """Build (if stale) and load the native library; cached per process."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if (
            not os.path.exists(_SO)
            or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        ):
            _build()
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            # stale/foreign-arch artifact (e.g. restored by checkout with
            # a tie mtime): rebuild from source and retry once
            os.unlink(_SO)
            _build()
            lib = ctypes.CDLL(_SO)
        lib.ccs_new.restype = ctypes.c_void_p
        lib.ccs_free.argtypes = [ctypes.c_void_p]
        lib.ccs_window_start.argtypes = [ctypes.c_void_p]
        lib.ccs_window_start.restype = ctypes.c_uint64
        lib.ccs_segment_count.argtypes = [ctypes.c_void_p]
        lib.ccs_segment_count.restype = ctypes.c_uint64
        lib.ccs_prune.argtypes = [ctypes.c_void_p]
        lib.ccs_resolve_batch.argtypes = [
            ctypes.c_void_p,  # set
            ctypes.c_char_p,  # blob
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,  # reads
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,  # writes
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64,  # read versions
            ctypes.c_uint64, ctypes.c_uint64,  # commit v, window
            ctypes.POINTER(ctypes.c_uint8),  # statuses out
        ]
        _lib = lib
        return lib


def native_available():
    try:
        load_library()
        return True
    except (NativeBuildError, OSError):
        return False


_PACKER_SRC = os.path.join(_HERE, "packer.cpp")
_PACKER_SO = os.path.join(_HERE, "fdbtpu_packer.so")
_packer_mod = None
_packer_failed = False


def _build_packer():
    import sys
    import sysconfig

    flags = [f"-I{sysconfig.get_paths()['include']}"]
    if sys.platform == "darwin":
        # CPython extensions resolve Python symbols at load time on mac
        flags += ["-undefined", "dynamic_lookup"]
    _compile(_PACKER_SRC, _PACKER_SO, flags)


def _import_packer():
    from importlib.machinery import ExtensionFileLoader
    from importlib.util import module_from_spec, spec_from_loader

    loader = ExtensionFileLoader("fdbtpu_packer", _PACKER_SO)
    spec = spec_from_loader("fdbtpu_packer", loader)
    mod = module_from_spec(spec)
    loader.exec_module(mod)
    return mod


def load_packer():
    """Build (if stale) and import the CPython packer extension; returns
    the module or None when a native toolchain isn't available (callers
    fall back to the numpy packer)."""
    global _packer_mod, _packer_failed
    with _lock:
        if _packer_mod is not None or _packer_failed:
            return _packer_mod
        try:
            if (
                not os.path.exists(_PACKER_SO)
                or os.path.getmtime(_PACKER_SO) < os.path.getmtime(_PACKER_SRC)
            ):
                _build_packer()
            try:
                _packer_mod = _import_packer()
            except ImportError:
                # stale/foreign-arch artifact (same hazard load_library
                # handles): rebuild from source and retry once
                os.unlink(_PACKER_SO)
                _build_packer()
                _packer_mod = _import_packer()
        except (NativeBuildError, ImportError, OSError):
            _packer_failed = True
            _packer_mod = None
        return _packer_mod


_STATUS_MAP = {0: COMMITTED, 1: CONFLICT, 2: TOO_OLD}


class NativeConflictSet:
    """Drop-in twin of resolver.skiplist.CpuConflictSet on the C++ core."""

    def __init__(self):
        self._lib = load_library()
        self._ptr = ctypes.c_void_p(self._lib.ccs_new())

    def __del__(self):
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr:
            self._lib.ccs_free(ptr)

    @property
    def window_start(self):
        return self._lib.ccs_window_start(self._ptr)

    @property
    def segment_count(self):
        return self._lib.ccs_segment_count(self._ptr)

    def prune(self):
        """Immediate GC of out-of-window segments (normally amortized)."""
        self._lib.ccs_prune(self._ptr)

    def resolve(self, txns, commit_version, new_window_start=None):
        """Resolve a batch in arrival order; returns list of statuses.

        Packing is allocation-lean on the hot path: a POINT key k packs
        once as ``k\\x00`` and its end span [k, k+\\x00) aliases the same
        blob bytes (begin = (off, len), end = (off, len+1)) — no
        per-range bytes concatenation, which dominated the profile. The
        commit proxy feeds this branch for the native backend:
        Resolver.wants_point_split routes single-key conflict ranges
        into the txn's point lanes (ADVICE r5: the branch was
        unreachable while only the tpu backend asked for the split)."""
        blob = bytearray()
        blob_extend, blob_append = blob.extend, blob.append
        reads, writes = [], []

        def pack(txn_reads, txn_writes, t):
            for out, points, ranges in (
                (reads, txn_reads[0], txn_reads[1]),
                (writes, txn_writes[0], txn_writes[1]),
            ):
                for b in points:
                    bo = len(blob)
                    blob_extend(b)
                    blob_append(0)
                    n = len(b)
                    out.append((t, bo, n, bo, n + 1))
                for b, e in ranges:
                    bo = len(blob)
                    blob_extend(b)
                    eo = len(blob)
                    blob_extend(e)
                    out.append((t, bo, len(b), eo, len(e)))

        rvs = np.empty(len(txns), np.uint64)
        for t, txn in enumerate(txns):
            rvs[t] = txn.read_version
            pack((txn.point_reads, txn.range_reads),
                 (txn.point_writes, txn.range_writes), t)

        r_arr = np.asarray(reads, np.int64).reshape(-1, 5)
        w_arr = np.asarray(writes, np.int64).reshape(-1, 5)
        statuses = np.empty(len(txns), np.uint8)
        return self._call_resolve(bytes(blob), r_arr, len(reads), w_arr,
                                  len(writes), rvs, len(txns),
                                  commit_version, new_window_start,
                                  statuses)

    def _call_resolve(self, blob, r_arr, n_reads, w_arr, n_writes, rvs,
                      n_txns, commit_version, new_window_start, statuses):
        i64p = ctypes.POINTER(ctypes.c_int64)
        self._lib.ccs_resolve_batch(
            self._ptr,
            blob,
            r_arr.ctypes.data_as(i64p), n_reads,
            w_arr.ctypes.data_as(i64p), n_writes,
            rvs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), n_txns,
            commit_version,
            new_window_start if new_window_start is not None else 0,
            statuses.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        return [_STATUS_MAP[s] for s in statuses.tolist()]

    def resolve_flat(self, flat, commit_version, new_window_start=None):
        """Resolve a columnar FlatTxnBatch (core/flatpack.py) with ZERO
        per-key Python: the concatenated entry blobs double as the ABI
        key blob. An entry is ``key ‖ \\x00-padding ‖ >I(len)``, so the
        raw key is ``blob[off : off+len]`` — and a point's end span
        ``k+\\x00`` is ``blob[off : off+len+1]``, the \\x00 supplied by
        the entry's own padding (by the first length byte when
        len == capacity, since capacity < 2^24). Offsets are pure
        arithmetic; entries sort by txn with one stable argsort (the C
        walk consumes rows strictly in txn order)."""
        n = len(flat)
        W = flat.num_limbs + 1
        W4 = 4 * W
        blob = flat.pr_blob + flat.pw_blob + flat.rr_blob + flat.rw_blob
        base_pw = len(flat.pr_blob)
        base_rr = base_pw + len(flat.pw_blob)
        base_rw = base_rr + len(flat.rr_blob)

        def lens_of(b):
            if not b:
                return np.zeros(0, np.int64)
            return np.frombuffer(b, dtype=">u4").reshape(-1, W)[:, -1] \
                .astype(np.int64)

        def point_rows(b, base, counts):
            t = np.repeat(np.arange(n), counts)
            off = base + np.arange(len(t), dtype=np.int64) * W4
            ln = lens_of(b)
            return np.stack([t, off, ln, off, ln + 1], axis=1)

        def range_rows(b, base, counts):
            t = np.repeat(np.arange(n), counts)
            ln = lens_of(b)  # interleaved lower/upper lengths
            off = base + np.arange(2 * len(t), dtype=np.int64) * W4
            return np.stack(
                [t, off[0::2], ln[0::2], off[1::2], ln[1::2]], axis=1
            )

        def side(prows, rrows):
            rows = np.concatenate([prows, rrows])
            # stable: a txn's points stay ahead of its ranges
            return np.ascontiguousarray(
                rows[np.argsort(rows[:, 0], kind="stable")]
            )

        r_arr = side(point_rows(flat.pr_blob, 0, flat.prc),
                     range_rows(flat.rr_blob, base_rr, flat.rrc))
        w_arr = side(point_rows(flat.pw_blob, base_pw, flat.pwc),
                     range_rows(flat.rw_blob, base_rw, flat.rwc))
        rvs = np.ascontiguousarray(flat.rv.astype(np.uint64))
        statuses = np.empty(n, np.uint8)
        return self._call_resolve(blob, r_arr, len(r_arr), w_arr,
                                  len(w_arr), rvs, n, commit_version,
                                  new_window_start, statuses)
