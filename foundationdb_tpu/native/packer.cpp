// Native batch packer: list[TxnRequest] -> ResolveBatch arrays, one C pass.
//
// The commit proxy's host-side serialization cost (resolver/packing.py
// BatchPacker.pack) bounds end-to-end throughput: the TPU kernel resolves
// >1M txns/sec, so the packer must too. Pure numpy tops out around 0.5M
// txns/sec on range-shaped batches because each txn is a Python object
// walk. This extension does the whole walk in C: per-txn op counts,
// conflict-range gather, big-endian limb encode, FNV-style hashing and
// coarse bucketing, writing directly into the preallocated numpy arrays.
//
// Ref parity: the role of CommitProxyServer.actor.cpp's batch
// serialization toward ResolveTransactionBatchRequest (the reference also
// does this in C++). The limb encoding and hash MUST stay in lockstep
// with core/keys.py KeyCodec and ops/intervals.fnv_hash; differential
// test: tests/test_packing_native.py.
//
// Contract (trusted internal ABI -- the Python caller allocates every
// array with the right shape/dtype; no shape checks here):
//   pack_into(txns, base_version, (PR, PW, RR, RW), num_limbs,
//             bucket_bits, arrays20) -> 0 ok | 1 overflow (caller
//             falls back to the numpy path, which normalizes)
// arrays20 (C-contiguous): rv u32[T]; txn_mask bool[T];
//   pr_key u32[T,PR,W], pr_hash u32[T,PR], pr_bucket i32[T,PR],
//   pr_mask bool[T,PR]; pw_* likewise; rr_b/rr_e u32[T,RR,W],
//   rr_lo/rr_hi i32[T,RR], rr_mask bool[T,RR]; rw_* likewise.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>

namespace {

struct Lane {
  uint32_t* key = nullptr;   // [T, N, W] (or begin for ranges)
  uint32_t* end = nullptr;   // [T, N, W] (ranges only)
  uint32_t* hash = nullptr;  // [T, N] (points only)
  int32_t* lo = nullptr;     // [T, N] bucket (ranges: begin bucket)
  int32_t* hi = nullptr;     // [T, N] bucket (ranges: end bucket)
  uint8_t* mask = nullptr;   // [T, N]
  Py_ssize_t cap = 0;        // N
};

// fnv_hash twin (ops/intervals.fnv_hash, packing.fnv_hash_np)
inline uint32_t fnv_hash(const uint32_t* limbs, int w) {
  uint32_t h = 2166136261u;
  for (int i = 0; i < w; i++) h = (h ^ limbs[i]) * 16777619u;
  h ^= h >> 16;
  h *= 0x7FEB352Du;
  h ^= h >> 15;
  return h;
}

// KeyCodec.encode_lower: big-endian 4-byte limbs, zero pad, length limb.
inline void encode_lower(const uint8_t* d, Py_ssize_t len, int L,
                         uint32_t* out) {
  const Py_ssize_t cap = 4 * (Py_ssize_t)L;
  const Py_ssize_t n = len < cap ? len : cap;
  for (int i = 0; i < L; i++) {
    Py_ssize_t b = 4 * (Py_ssize_t)i;
    uint32_t v = 0;
    if (b < n) {
      v |= (uint32_t)d[b] << 24;
      if (b + 1 < n) v |= (uint32_t)d[b + 1] << 16;
      if (b + 2 < n) v |= (uint32_t)d[b + 2] << 8;
      if (b + 3 < n) v |= (uint32_t)d[b + 3];
    }
    out[i] = v;
  }
  out[L] = (uint32_t)n;
}

// KeyCodec.encode_upper: same for in-capacity keys; over-capacity upper
// bounds round up to the prefix successor (conservative widening).
inline void encode_upper(const uint8_t* d, Py_ssize_t len, int L,
                         uint32_t* out) {
  const Py_ssize_t cap = 4 * (Py_ssize_t)L;
  encode_lower(d, len, L, out);
  if (len <= cap) return;
  for (int i = L - 1; i >= 0; i--) {
    if (out[i] != 0xFFFFFFFFu) {
      out[i] += 1;
      for (int j = i + 1; j < L; j++) out[j] = 0;
      out[L] = 0;
      return;
    }
    out[i] = 0;
  }
  for (int i = 0; i < L; i++) out[i] = 0xFFFFFFFFu;
  out[L] = (uint32_t)(cap + 1);
}

inline int32_t bucket_of(uint32_t first_limb, int bucket_bits) {
  return (int32_t)(first_limb >> (32 - bucket_bits));
}

struct Names {
  PyObject* read_version;
  PyObject* point_reads;
  PyObject* point_writes;
  PyObject* range_reads;
  PyObject* range_writes;
};

// Borrowed-ref sequence item access tolerating list or tuple.
inline PyObject* seq_item(PyObject* s, Py_ssize_t i) {
  if (PyList_Check(s)) return PyList_GET_ITEM(s, i);
  if (PyTuple_Check(s)) return PyTuple_GET_ITEM(s, i);
  return nullptr;
}

inline Py_ssize_t seq_len(PyObject* s) {
  if (PyList_Check(s)) return PyList_GET_SIZE(s);
  if (PyTuple_Check(s)) return PyTuple_GET_SIZE(s);
  return -1;
}

// Fill one point op slot. Returns false on type error (exception set).
inline bool fill_point(PyObject* key, Lane& lane, Py_ssize_t t,
                       Py_ssize_t slot, int L, int W, int bucket_bits) {
  char* d;
  Py_ssize_t len;
  if (PyBytes_AsStringAndSize(key, &d, &len) < 0) return false;
  uint32_t* out = lane.key + (t * lane.cap + slot) * W;
  encode_lower((const uint8_t*)d, len, L, out);
  lane.hash[t * lane.cap + slot] = fnv_hash(out, W);
  lane.lo[t * lane.cap + slot] = bucket_of(out[0], bucket_bits);
  lane.mask[t * lane.cap + slot] = 1;
  return true;
}

inline bool fill_range(PyObject* pair, Lane& lane, Py_ssize_t t,
                       Py_ssize_t slot, int L, int W, int bucket_bits) {
  if (!pair || seq_len(pair) < 2) {
    PyErr_SetString(PyExc_TypeError, "range must be a (begin, end) pair");
    return false;
  }
  PyObject* kb = seq_item(pair, 0);
  PyObject* ke = seq_item(pair, 1);
  char *db, *de;
  Py_ssize_t lb, le;
  if (PyBytes_AsStringAndSize(kb, &db, &lb) < 0) return false;
  if (PyBytes_AsStringAndSize(ke, &de, &le) < 0) return false;
  uint32_t* ob = lane.key + (t * lane.cap + slot) * W;
  uint32_t* oe = lane.end + (t * lane.cap + slot) * W;
  encode_lower((const uint8_t*)db, lb, L, ob);
  encode_upper((const uint8_t*)de, le, L, oe);
  lane.lo[t * lane.cap + slot] = bucket_of(ob[0], bucket_bits);
  lane.hi[t * lane.cap + slot] = bucket_of(oe[0], bucket_bits);
  lane.mask[t * lane.cap + slot] = 1;
  return true;
}

struct Bufs {
  Py_buffer views[20];
  int n = 0;
  ~Bufs() {
    for (int i = 0; i < n; i++) PyBuffer_Release(&views[i]);
  }
  void* get(PyObject* arrays, int i) {
    PyObject* o = PyTuple_GET_ITEM(arrays, i);
    if (PyObject_GetBuffer(o, &views[n], PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) <
        0)
      return nullptr;
    return views[n++].buf;
  }
};

PyObject* pack_into(PyObject*, PyObject* args) {
  static Names names = {
      PyUnicode_InternFromString("read_version"),
      PyUnicode_InternFromString("point_reads"),
      PyUnicode_InternFromString("point_writes"),
      PyUnicode_InternFromString("range_reads"),
      PyUnicode_InternFromString("range_writes"),
  };
  PyObject* txns;
  long long base_version;
  int pr_cap, pw_cap, rr_cap, rw_cap, num_limbs, bucket_bits;
  PyObject* arrays;
  if (!PyArg_ParseTuple(args, "OL(iiii)iiO!", &txns, &base_version, &pr_cap,
                        &pw_cap, &rr_cap, &rw_cap, &num_limbs, &bucket_bits,
                        &PyTuple_Type, &arrays))
    return nullptr;
  if (!PyList_Check(txns)) {
    PyErr_SetString(PyExc_TypeError, "txns must be a list");
    return nullptr;
  }
  if (PyTuple_GET_SIZE(arrays) != 20) {
    PyErr_SetString(PyExc_TypeError, "arrays must be a 20-tuple");
    return nullptr;
  }
  const int L = num_limbs, W = num_limbs + 1;
  const Py_ssize_t n = PyList_GET_SIZE(txns);

  Bufs bufs;
  uint32_t* rv = (uint32_t*)bufs.get(arrays, 0);
  uint8_t* txn_mask = (uint8_t*)bufs.get(arrays, 1);
  Lane pr, pw, rr, rw;
  pr.cap = pr_cap;
  pr.key = (uint32_t*)bufs.get(arrays, 2);
  pr.hash = (uint32_t*)bufs.get(arrays, 3);
  pr.lo = (int32_t*)bufs.get(arrays, 4);
  pr.mask = (uint8_t*)bufs.get(arrays, 5);
  pw.cap = pw_cap;
  pw.key = (uint32_t*)bufs.get(arrays, 6);
  pw.hash = (uint32_t*)bufs.get(arrays, 7);
  pw.lo = (int32_t*)bufs.get(arrays, 8);
  pw.mask = (uint8_t*)bufs.get(arrays, 9);
  rr.cap = rr_cap;
  rr.key = (uint32_t*)bufs.get(arrays, 10);
  rr.end = (uint32_t*)bufs.get(arrays, 11);
  rr.lo = (int32_t*)bufs.get(arrays, 12);
  rr.hi = (int32_t*)bufs.get(arrays, 13);
  rr.mask = (uint8_t*)bufs.get(arrays, 14);
  rw.cap = rw_cap;
  rw.key = (uint32_t*)bufs.get(arrays, 15);
  rw.end = (uint32_t*)bufs.get(arrays, 16);
  rw.lo = (int32_t*)bufs.get(arrays, 17);
  rw.hi = (int32_t*)bufs.get(arrays, 18);
  rw.mask = (uint8_t*)bufs.get(arrays, 19);
  if (PyErr_Occurred()) return nullptr;

  // Inactive point slots carry the hash of the all-zero key (the numpy
  // path hashes the whole array); the caller pre-fills hash arrays with
  // that constant, so this pass only writes active slots.
  for (Py_ssize_t t = 0; t < n; t++) {
    PyObject* txn = PyList_GET_ITEM(txns, t);
    PyObject* rv_obj = PyObject_GetAttr(txn, names.read_version);
    if (!rv_obj) return nullptr;
    long long v = PyLong_AsLongLong(rv_obj);
    Py_DECREF(rv_obj);
    if (v == -1 && PyErr_Occurred()) return nullptr;
    long long off = v - base_version;
    if (off < 0) off = 0;
    if (off > 0xFFFFFFFFll) off = 0xFFFFFFFFll;
    rv[t] = (uint32_t)off;
    txn_mask[t] = 1;

    PyObject* lists[4];
    static PyObject** lnames[4] = {&names.point_reads, &names.point_writes,
                                   &names.range_reads, &names.range_writes};
    const Py_ssize_t caps[4] = {pr_cap, pw_cap, rr_cap, rw_cap};
    Lane* lanes[4] = {&pr, &pw, &rr, &rw};
    for (int k = 0; k < 4; k++) {
      lists[k] = PyObject_GetAttr(txn, *lnames[k]);
      if (!lists[k]) {
        for (int j = 0; j < k; j++) Py_DECREF(lists[j]);
        return nullptr;
      }
    }
    bool ok = true, overflow = false;
    for (int k = 0; k < 4 && ok; k++) {
      Py_ssize_t cnt = seq_len(lists[k]);
      if (cnt < 0) {
        PyErr_SetString(PyExc_TypeError, "op lists must be list or tuple");
        ok = false;
        break;
      }
      if (cnt > caps[k]) {
        overflow = true;  // caller's numpy path normalizes (spill/coalesce)
        break;
      }
      for (Py_ssize_t i = 0; i < cnt && ok; i++) {
        PyObject* item = seq_item(lists[k], i);
        ok = (k < 2)
                 ? fill_point(item, *lanes[k], t, i, L, W, bucket_bits)
                 : fill_range(item, *lanes[k], t, i, L, W, bucket_bits);
      }
    }
    for (int k = 0; k < 4; k++) Py_DECREF(lists[k]);
    if (!ok) return nullptr;
    if (overflow) return PyLong_FromLong(1);
  }
  return PyLong_FromLong(0);
}

PyMethodDef methods[] = {
    {"pack_into", pack_into, METH_VARARGS,
     "Pack TxnRequests into preallocated ResolveBatch arrays; 0 ok, 1 "
     "overflow (fall back to the numpy path)."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {PyModuleDef_HEAD_INIT, "fdbtpu_packer",
                      "Native ResolveBatch packer", -1, methods};

}  // namespace

PyMODINIT_FUNC PyInit_fdbtpu_packer(void) { return PyModule_Create(&module); }
