// Native host ConflictSet — the CPU fast path behind resolver_backend="native".
//
// Role parity: fdbserver/SkipList.cpp's ConflictSet::detectConflicts (the
// reference keeps ~5s of committed write ranges in a lock-free skip list and
// stabs it per read range). This is an independent design, not a port: the
// history is a *flattened interval map* — an ordered set of non-overlapping
// segments of the keyspace, each carrying the newest commit version that
// wrote any part of it. Recording a write splices the segment list
// (split partials, max-merge covered parts); a read conflict check is a
// range-max over the overlapping segments. Both are O(log n + k).
//
// The ABI is batch-oriented to amortize FFI cost: one call resolves a whole
// commit batch from packed offset arrays (the same packing philosophy as the
// TPU kernel's device arrays — contiguous buffers, no per-range calls).

#include <cstdint>
#include <cstring>
#include <map>
#include <string>

namespace {

using Key = std::string;

struct ConflictSet {
  // segment [iter->first, iter->second.end) wrote at version iter->second.v
  struct Seg {
    Key end;
    uint64_t v;
  };
  std::map<Key, Seg> segs;
  uint64_t window_start = 0;
  uint32_t advances_since_prune = 0;

  // Newest version among segments overlapping [b, e). 0 = none.
  uint64_t query_max(const Key& b, const Key& e) const {
    if (segs.empty() || b >= e) return 0;
    uint64_t best = 0;
    auto it = segs.upper_bound(b);
    if (it != segs.begin()) {
      auto prev = std::prev(it);
      if (prev->second.end > b) best = prev->second.v;
    }
    for (; it != segs.end() && it->first < e; ++it)
      if (it->second.v > best) best = it->second.v;
    return best;
  }

  // Record write [b, e) at version v (v is >= every version already
  // present, since commit versions are handed out in order; we still
  // max() defensively so recovery replays cannot regress history).
  void record(const Key& b, const Key& e, uint64_t v) {
    if (b >= e) return;
    // first segment whose begin is >= b; a strictly-earlier segment can
    // straddle b and must be split so the loop below sees a clean edge
    auto it = segs.lower_bound(b);
    if (it != segs.begin()) {
      auto prev = std::prev(it);  // prev->first < b by lower_bound
      if (prev->second.end > b) {
        Seg right{prev->second.end, prev->second.v};
        prev->second.end = b;
        it = segs.emplace(b, right).first;
      }
    }
    Key cur = b;
    while (cur < e) {
      if (it == segs.end() || it->first >= e) {
        // trailing gap [cur, e)
        segs.emplace(cur, Seg{e, v});
        break;
      }
      if (it->first > cur) {
        // gap [cur, it->first)
        it = segs.emplace(cur, Seg{it->first, v}).first;
        ++it;
        cur = (it == segs.end()) ? e : std::prev(it)->second.end;
        continue;
      }
      // segment starts at cur
      if (it->second.end > e) {
        // split at e; left part gets max version
        Seg right{it->second.end, it->second.v};
        it->second.end = e;
        if (v > it->second.v) it->second.v = v;
        segs.emplace(e, right);
        break;
      }
      if (v > it->second.v) it->second.v = v;
      cur = it->second.end;
      ++it;
    }
  }

  // Drop segments entirely older than the window (lazy GC; the reference
  // advances oldestVersion and unlinks dead skip-list nodes the same way).
  void prune() {
    for (auto it = segs.begin(); it != segs.end();) {
      if (it->second.v < window_start)
        it = segs.erase(it);
      else
        ++it;
    }
  }
};

inline Key mk(const uint8_t* blob, uint64_t off, uint32_t len) {
  return Key(reinterpret_cast<const char*>(blob) + off, len);
}

}  // namespace

extern "C" {

void* ccs_new() { return new ConflictSet(); }
void ccs_free(void* p) { delete static_cast<ConflictSet*>(p); }

uint64_t ccs_window_start(void* p) {
  return static_cast<ConflictSet*>(p)->window_start;
}

uint64_t ccs_segment_count(void* p) {
  return static_cast<ConflictSet*>(p)->segs.size();
}

// Resolve one commit batch.
//   blob, offsets/lengths: all keys packed into one byte buffer.
//   Ranges are rows of 5 x int64: {txn, b_off, b_len, e_off, e_len},
//   read ranges and write ranges in separate arrays, sorted by txn.
//   statuses out: 0 = COMMITTED, 1 = CONFLICT, 2 = TOO_OLD
//   (matches foundationdb_tpu.core.status).
void ccs_resolve_batch(void* p, const uint8_t* blob,
                       const int64_t* reads, int64_t n_reads,
                       const int64_t* writes, int64_t n_writes,
                       const uint64_t* read_versions, int64_t n_txns,
                       uint64_t commit_version, uint64_t new_window_start,
                       uint8_t* statuses) {
  auto* cs = static_cast<ConflictSet*>(p);
  int64_t ri = 0, wi = 0;
  for (int64_t t = 0; t < n_txns; ++t) {
    if (read_versions[t] < cs->window_start) {
      statuses[t] = 2;  // TOO_OLD
      while (ri < n_reads && reads[ri * 5] == t) ++ri;
      while (wi < n_writes && writes[wi * 5] == t) ++wi;
      continue;
    }
    bool conflict = false;
    for (; ri < n_reads && reads[ri * 5] == t; ++ri) {
      if (conflict) continue;
      const int64_t* r = reads + ri * 5;
      Key b = mk(blob, r[1], static_cast<uint32_t>(r[2]));
      Key e = mk(blob, r[3], static_cast<uint32_t>(r[4]));
      if (cs->query_max(b, e) > read_versions[t]) conflict = true;
    }
    if (conflict) {
      statuses[t] = 1;  // CONFLICT
      while (wi < n_writes && writes[wi * 5] == t) ++wi;
      continue;
    }
    statuses[t] = 0;  // COMMITTED — record its writes at once, so later
    // txns in this batch conflict against them (intra-batch ordering)
    for (; wi < n_writes && writes[wi * 5] == t; ++wi) {
      const int64_t* w = writes + wi * 5;
      Key b = mk(blob, w[1], static_cast<uint32_t>(w[2]));
      Key e = mk(blob, w[3], static_cast<uint32_t>(w[4]));
      cs->record(b, e, commit_version);
    }
  }
  if (new_window_start > cs->window_start) {
    cs->window_start = new_window_start;
    // amortize GC: the proxy advances the window every batch, and a full
    // map scan per batch would dominate; raising window_start alone is
    // already correct (reads below it are TOO_OLD before any stab, and
    // stale segments can never out-version an admissible read)
    if (++cs->advances_since_prune >= 64) {
      cs->advances_since_prune = 0;
      cs->prune();
    }
  }
}

// Force a GC pass (tests; checkpoint/quiesce paths).
void ccs_prune(void* p) { static_cast<ConflictSet*>(p)->prune(); }

}  // extern "C"
