"""Multi-chip resolver: shard_map over a jax Mesh.

FDB scales conflict detection by key-range-sharding resolvers across
processes, with the commit proxy fanning out and AND-ing verdicts
(ref: fdbserver/CommitProxyServer.actor.cpp resolution fan-out,
fdbserver/Resolver.actor.cpp). The TPU analog keeps the whole resolver
fleet inside ONE jit program over a device mesh: ops/conflict.py's
``resolve_batch(axis_name='rs')`` runs as one SPMD program where every
device owns a shard of the conflict history (hash-sharded point table,
bucket-sharded range ring) and verdicts combine with psum/pmax over ICI —
the XLA-collective replacement for the reference's FlowTransport RPC.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from foundationdb_tpu.ops import conflict as ck

# jax moved shard_map to the top level (and renamed check_rep →
# check_vma) around 0.6; older runtimes only ship the experimental
# module. One gated alias keeps the kernel running on both.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _CHECK_KW = "check_vma"
else:  # pragma: no cover — exercised on older-jax containers
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"

AXIS = "rs"


def default_mesh(n_devices=None):
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (AXIS,))


def lane_shards(arr):
    """The per-device shards of ``arr`` in stable lane order (device
    id) — the HOST-side handle set the device profiler blocks one by
    one to measure per-lane dispatch wall (utils/deviceprofile.py).
    Empty for values without addressable shards (plain numpy, tracers),
    so callers can no-op on host backends."""
    try:
        shards = arr.addressable_shards
    except AttributeError:
        return []
    return sorted(shards, key=lambda s: s.device.id)


def _state_specs(axes=AXIS):
    return ck.ResolverState(
        window_start=P(),  # replicated scalar
        ht=P(axes),
        ring_b=P(axes),
        ring_e=P(axes),
        ring_v=P(axes),
        ring_lo=P(axes),
        ring_hi=P(axes),
        ring_mask=P(axes),
        ring_head=P(axes),  # [n] — one cursor per shard
        range_L=P(),  # replicated coarse summaries (pmax-synced)
        range_R=P(),
        point_coarse=P(),
    )


def _batch_specs():
    return jax.tree.map(lambda _: P(), ck.ResolveBatch(*ck.ResolveBatch._fields))


# ShardBatch fields that stay replicated across lanes (everything else
# is a per-lane compacted slot array, sharded on its leading axis)
_SHARD_REPLICATED = {"rv", "txn_mask", "cv", "new_window_start"}


def _shard_batch_specs(axes=AXIS, scan=False):
    """PartitionSpecs for a ShardBatch: entry slot arrays split on the
    lane axis (leading dim n*Q → per-lane Q), verdict-fold inputs
    replicated. ``scan=True`` shifts the lane axis behind the batch
    axis (stacked [B, n*Q, ...] inputs for the scan path)."""

    def spec(name):
        if name in _SHARD_REPLICATED:
            return P()
        return P(None, axes) if scan else P(axes)

    return ck.ShardBatch(*(spec(f) for f in ck.ShardBatch._fields))


class ShardedResolverKernel:
    """The resolver fleet as one SPMD program.

    Per-device history capacity equals ``params`` sizes, so global
    capacity scales linearly with mesh size (hash table 2^HB * n, ring
    KR * n), while the batch is replicated — exactly the axis FDB scales
    resolvers on.
    """

    def __init__(self, params: ck.ResolverParams, mesh=None, donate=True,
                 make_state=True):
        ck.validate_params(params)
        self.params = params
        self.mesh = mesh if mesh is not None else default_mesh()
        self.n = self.mesh.devices.size
        # hybrid host×chip meshes (parallel/distributed.py) shard state
        # over every axis; the flat single-host mesh over the one axis
        self.axes = tuple(self.mesh.axis_names)
        self.spec_axes = self.axes if len(self.axes) > 1 else self.axes[0]

        fn = functools.partial(
            ck.resolve_batch, params=params, axis_name=self.spec_axes,
            n_shards=self.n,
        )
        sharded = _shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(_state_specs(self.spec_axes), _batch_specs()),
            out_specs=(P(), P(), _state_specs(self.spec_axes)),
            **{_CHECK_KW: False},
        )
        self._step = jax.jit(sharded, donate_argnums=(0,) if donate else ())

        scan_sharded = _shard_map(
            ck.scan_of(fn),
            mesh=self.mesh,
            in_specs=(_state_specs(self.spec_axes), _batch_specs()),
            out_specs=(_state_specs(self.spec_axes), P()),
            **{_CHECK_KW: False},
        )
        self._scan_step = jax.jit(
            scan_sharded, donate_argnums=(0,) if donate else ()
        )
        # make_state=False: a caller sharing state with a twin kernel
        # (MeshResolver's point-fast variant) skips the throwaway arrays
        self.state = self.init_state() if make_state else None

    def init_state(self):
        p, n = self.params, self.n
        kr, c, w = p.ring_capacity, 1 << p.bucket_bits, p.key_width
        u32 = jnp.uint32
        axes = self.spec_axes

        def put(arr, spec):
            return jax.device_put(arr, NamedSharding(self.mesh, spec))

        return ck.ResolverState(
            window_start=put(jnp.zeros((), u32), P()),
            ht=put(jnp.zeros((n << p.hash_bits,), u32), P(axes)),
            ring_b=put(jnp.zeros((n * kr, w), u32), P(axes)),
            ring_e=put(jnp.zeros((n * kr, w), u32), P(axes)),
            ring_v=put(jnp.zeros((n * kr,), u32), P(axes)),
            ring_lo=put(jnp.zeros((n * kr,), jnp.int32), P(axes)),
            ring_hi=put(jnp.zeros((n * kr,), jnp.int32), P(axes)),
            ring_mask=put(jnp.zeros((n * kr,), bool), P(axes)),
            ring_head=put(jnp.zeros((n,), jnp.int32), P(axes)),
            range_L=put(jnp.zeros((c,), u32), P()),
            range_R=put(jnp.zeros((c,), u32), P()),
            point_coarse=put(jnp.zeros((c,), u32), P()),
        )

    def resolve(self, batch: ck.ResolveBatch):
        status, accepted, self.state = self._step(self.state, batch)
        return status, accepted

    def resolve_many(self, batches: ck.ResolveBatch):
        """Resolve a stack of batches (leading axis B) in one dispatch:
        lax.scan inside the sharded program, so the whole fleet stays on
        device for B consecutive commit batches. Returns statuses[B, T]."""
        self.state, statuses = self._scan_step(self.state, batches)
        return statuses


class PreshardedResolverKernel(ShardedResolverKernel):
    """The compacted-lane fleet: one SPMD program over host-presharded
    ShardBatches (ops/conflict.resolve_batch_presharded).

    The dense ``ShardedResolverKernel`` replicates the whole batch to
    every lane and carves ownership in-kernel — per-lane work never
    shrinks, so k lanes cost k× the FLOPs of one. Here the host router
    (resolver/packing.ShardRouter) sends each entry only to the lane(s)
    owning its keys, so the ring scan and the pairwise conflict matrix
    shrink ~1/n per lane while history capacity still scales n×. State
    layout and placement are inherited unchanged (``ring_capacity`` is
    the PER-LANE ring size, as before); only the batch specs and the
    kernel body differ. Ref: CommitProxyServer.actor.cpp's resolution
    fan-out, collapsed into one collective dispatch.
    """

    def __init__(self, params: ck.ResolverParams, mesh=None, donate=True,
                 make_state=True):
        ck.validate_presharded_params(params)
        self.params = params
        self.mesh = mesh if mesh is not None else default_mesh()
        self.n = self.mesh.devices.size
        self.axes = tuple(self.mesh.axis_names)
        self.spec_axes = self.axes if len(self.axes) > 1 else self.axes[0]

        fn = functools.partial(
            ck.resolve_batch_presharded, params=params,
            axis_name=self.spec_axes,
        )
        sharded = _shard_map(
            fn,
            mesh=self.mesh,
            in_specs=(_state_specs(self.spec_axes),
                      _shard_batch_specs(self.spec_axes)),
            out_specs=(P(), P(), _state_specs(self.spec_axes)),
            **{_CHECK_KW: False},
        )
        self._step = jax.jit(sharded, donate_argnums=(0,) if donate else ())

        scan_sharded = _shard_map(
            ck.scan_of(fn),
            mesh=self.mesh,
            in_specs=(_state_specs(self.spec_axes),
                      _shard_batch_specs(self.spec_axes, scan=True)),
            out_specs=(_state_specs(self.spec_axes), P()),
            **{_CHECK_KW: False},
        )
        self._scan_step = jax.jit(
            scan_sharded, donate_argnums=(0,) if donate else ()
        )
        self.state = self.init_state() if make_state else None
