"""Multi-host deployment: process groups and hybrid ICI/DCN meshes.

Ref parity: the role FlowTransport + cluster connection strings play in
the reference — how N machines become one transaction system — mapped
to JAX's runtime: ``jax.distributed`` forms the process group (the
coordinator is the analog of the cluster file's coordinators for
*compute* membership), and a hybrid ``Mesh`` lays out resolver shards so
the verdict collectives (psum/pmax in ops/conflict.py) ride ICI within a
host's chips and only the small reductions cross DCN between hosts.

Single-process use is a no-op: every helper degrades to the local
devices, so the same code runs on a laptop CPU mesh, one TPU host, or a
multi-host slice.
"""

import os

import jax
import numpy as np
from jax.sharding import Mesh

from foundationdb_tpu.parallel.mesh import AXIS

HOST_AXIS = "hosts"


def initialize(coordinator_address=None, num_processes=None, process_id=None,
               **kw):
    """Join (or form) the multi-host process group.

    Mirrors ``jax.distributed.initialize`` but is safe to call
    unconditionally: with no coordinator configured (args or
    JAX_COORDINATOR_ADDRESS / standard cluster env), it is a no-op and
    the framework stays single-process. Returns (process_index,
    process_count).
    """
    addr = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    # decide from configuration alone — touching any device API first
    # (even process_count()) initializes the XLA backend, after which
    # jax.distributed.initialize refuses to run
    if addr:
        try:
            jax.distributed.initialize(
                coordinator_address=addr,
                num_processes=num_processes,
                process_id=process_id,
                **kw,
            )
        except RuntimeError:
            # already part of a process group (double-initialize), or the
            # backend was touched first in a single-process run — both
            # leave jax.process_* as the source of truth below
            pass
    return jax.process_index(), jax.process_count()


def fleet_mesh(n_devices=None):
    """A resolver-fleet mesh spanning every process's devices.

    Multi-host: a 2-D ('hosts', 'rs') mesh — hosts over DCN, each host's
    chips over ICI — built so that consecutive 'rs' coordinates stay on
    one host (collectives over 'rs' never leave ICI). Single-host: the
    flat 1-D ('rs',) mesh from parallel.mesh.
    """
    if jax.process_count() <= 1:
        devs = jax.devices()
        if n_devices is not None:
            devs = devs[:n_devices]
        return Mesh(np.array(devs), (AXIS,))
    per_host = jax.local_device_count()
    total = jax.process_count() * per_host
    if n_devices is not None and n_devices != total:
        raise ValueError(
            f"n_devices={n_devices} cannot subset a multi-host fleet of "
            f"{total} devices: every host's chips participate in the mesh"
        )
    grid = np.array(jax.devices()).reshape(jax.process_count(), per_host)
    return Mesh(grid, (HOST_AXIS, AXIS))


def shard_axes(mesh):
    """The mesh axes conflict state shards over.

    On a hybrid mesh the history shards across BOTH axes (every chip in
    the fleet owns a slice), so specs use ('hosts', 'rs') where the flat
    mesh uses 'rs'.
    """
    return (
        (HOST_AXIS, AXIS) if HOST_AXIS in mesh.axis_names else (AXIS,)
    )
