"""Client-side transaction repair — conflicted txns fixed, not rerun.

Ref: "Repairing Conflicts among MVCC Transactions" (arxiv 1603.00542):
an OCC-rejected transaction usually failed because of a handful of
conflicting writes; everything else it read is still valid, so the txn
can be repaired from the conflicting writes instead of restarted from
scratch. The restart-from-scratch loop pays a backoff sleep, a fresh
GRV round trip, and a full re-read of every key — at TPC-C's measured
63% conflict rate that loop is most of the cluster's work.

The engine records the transaction's operation log during the attempt:
every storage-backed point read (key → value) and range read
(signature → rows). On ``not_committed`` carrying conflicting-key info
(``report_conflicting_keys``, which the engine forces on), the proxy
also reports ``conflict_version`` — the commit version whose writes
rejected the txn. That version is the whole trick:

- a read range NOT in the conflict report was checked by the resolver
  against every write in ``(read_version, conflict_version]`` and found
  clean — its recorded value **provably equals its value at
  conflict_version**;
- the conflicting keys are re-read — ONLY them — at exactly
  ``conflict_version``.

Together that reconstructs a consistent snapshot at conflict_version
without a GRV and with no storage traffic beyond the conflicting keys.
Two outcomes:

- **replay** (read-set digest match — every refreshed value equals the
  recorded one, i.e. a spurious/false-positive conflict): the recorded
  op log replays verbatim — the transaction keeps its mutations and
  conflict ranges, moves its read version to conflict_version, and
  resubmits without re-running the body (``Transaction.repair_ready``).
- **fallback** (digest mismatch — a conflicting value changed, so the
  recorded writes may embed stale reads; value-dependent control flow
  cannot be replayed): control returns to the retry loop and the body
  re-runs — but the restart rides the repair seam: read version =
  conflict_version (no GRV), reads served from the verified cache
  (conflicting keys already refreshed), and no backoff sleep for the
  first ``txn_repair_max_rounds`` rounds.

Serializability is untouched: every resubmission carries its full read
conflict ranges and the resolver re-validates ``(conflict_version,
new_commit_version]`` as usual — repair only changes where the reads
come from, never what is declared read. Repair outcomes ride the
commit-proxy metrics registry (``repair_attempts`` / ``repair_commits``
/ ``repair_fallbacks``) into status rollups and fdbcli status. The
engine draws no entropy and reads no clock (FL001): a seeded simulation
repairs byte-identically.
"""

from foundationdb_tpu.core.errors import FDBError
from foundationdb_tpu.utils import metrics as metrics_mod


class RepairEngine:
    """One attempt's operation log: storage-backed reads by key (point)
    and by call signature (range), plus replayability state."""

    __slots__ = ("point_reads", "range_reads", "unreplayable", "rounds")

    def __init__(self, rounds=0):
        self.point_reads = {}  # key -> value as first read this attempt
        self.range_reads = {}  # (b, e, limit, reverse) -> tuple(rows)
        # reads the engine cannot verify at a later version (selector
        # resolution, size estimates, special-key reads): the op log
        # still seeds the fallback rerun, but never auto-replays
        self.unreplayable = False
        self.rounds = rounds  # consecutive repair rounds this txn spent


def _overlaps_point(key, ranges):
    for b, e in ranges:
        if b <= key < e:
            return True
    return False


def _overlaps_span(begin, end, ranges):
    for b, e in ranges:
        if b < end and begin < e:
            return True
    return False


def note(cluster, name, n=1):
    """Count a repair outcome on the commit-proxy registry this client
    talks to (the PR-4 per-role registries — in-process clusters fold
    it straight into status rollups)."""
    if n <= 0 or not metrics_mod.enabled():
        return
    cp = getattr(cluster, "commit_proxy", None)
    reg = getattr(cp, "metrics", None)
    if reg is None and cp is not None:
        inners = getattr(cp, "inners", None)  # ProxyFleet
        if inners:
            reg = getattr(inners[0], "metrics", None)
    if reg is not None:
        reg.counter(name).inc(n)


def attempt(tr, error):
    """The ``Transaction.on_error`` repair hook: returns True when the
    transaction was repaired (replay-ready or cache-seeded, read
    version moved, no backoff owed) and False when the caller must run
    the ordinary cold-restart path."""
    eng = tr._repair
    if eng is None or error.code != 1020:
        return False
    ranges = getattr(error, "conflicting_key_ranges", None)
    cv = getattr(error, "conflict_version", None)
    if ranges is None or cv is None:
        return False  # a blanket 1020 (e.g. ResolverDown): no repair basis
    if tr._special_writes or tr._watches_pending:
        return False  # management/watch txns restart cold
    rounds = eng.rounds + 1
    if rounds > tr._knobs.txn_repair_max_rounds:
        return False  # livelock bound: back to honest backoff
    note(tr._cluster, "repair_attempts")
    # re-read ONLY the conflicting keys, at exactly the version whose
    # writes rejected us; everything else is resolver-proven unchanged
    cache = {}
    digest_ok = not eng.unreplayable
    try:
        for k, v0 in eng.point_reads.items():
            if _overlaps_point(k, ranges):
                v1 = tr._cluster.read_storage(k).get(k, cv)
                cache[k] = v1
                if v1 != v0:
                    digest_ok = False
            else:
                cache[k] = v0
        range_cache = {}
        for sig, rows0 in eng.range_reads.items():
            b, e, limit, reverse = sig
            if _overlaps_span(b, e, ranges):
                st = tr._cluster.read_storage(b)
                rows1 = tuple(st.get_range(b, e, cv, limit=limit,
                                           reverse=reverse))
                range_cache[sig] = rows1
                if rows1 != rows0:
                    digest_ok = False
            else:
                range_cache[sig] = rows0
    except FDBError:
        # the refresh itself failed (conflict_version already out of a
        # replica's window, storage mid-recruitment): restart cold
        return False
    if digest_ok:
        # spurious conflict: the op log replays verbatim — keep writes,
        # mutations, and conflict ranges; only the read version moves.
        # The runner sees ``repair_ready`` and resubmits without
        # re-running the body.
        eng.rounds = rounds
        eng.point_reads.update(cache)
        eng.range_reads.update(range_cache)
        tr._read_version = cv
        tr._state = "active"
        tr._repair_ready = True
        tr._repair_assisted = True
        return True
    # value-dependent (the read-set digest moved): the recorded writes
    # may embed stale reads, so the body must re-run — seeded. Same
    # keep-set as the cold restart, minus the backoff sleep.
    note(tr._cluster, "repair_fallbacks")
    keep = (tr._retries, tr._backoff, tr._retry_limit,
            tr._max_retry_delay, tr._timeout_s,
            tr._idempotency_id, tr._auto_idempotency,
            tr._trace_forced)
    tr._reset()
    (tr._retries, tr._backoff, tr._retry_limit,
     tr._max_retry_delay, tr._timeout_s,
     tr._idempotency_id, tr._auto_idempotency,
     tr._trace_forced) = keep
    tr._repair = RepairEngine(rounds=rounds)
    tr._read_version = cv
    tr._repair_cache = cache
    tr._repair_range_cache = range_cache
    tr._repair_assisted = True
    return True
