"""Client Transaction: snapshot reads, read-your-writes, OCC commit.

Ref parity: fdbclient/NativeAPI.actor.cpp (Transaction) layered with
fdbclient/ReadYourWrites.actor.cpp, exposed in the shape of FDB's Python
binding (bindings/python/fdb/impl.py): tr[key], tr[b:e], tr.get_range,
atomic helpers, snapshot view, watch, on_error retry protocol.
"""

import time

from foundationdb_tpu.core import flatpack
from foundationdb_tpu.core.errors import FDBError, err
from foundationdb_tpu.core.keys import (
    MAX_KEY_SIZE,
    MAX_VALUE_SIZE,
    KeySelector,
    key_successor,
    strinc,
)
from foundationdb_tpu.core.mutations import Mutation, Op
from foundationdb_tpu.core.versions import Versionstamp
from foundationdb_tpu.server.proxy import CommitRequest
from foundationdb_tpu.txn import specialkeys
from foundationdb_tpu.txn.futures import FutureRange, FutureValue
from foundationdb_tpu.txn.rows import WriteMap
from foundationdb_tpu.utils import span as span_mod
from foundationdb_tpu.utils.backoff import Backoff

_INVALID = object()


def _check_key(key, limit=MAX_KEY_SIZE):
    key = bytes(key)
    if len(key) > limit:
        raise err("key_too_large")
    return key


def _check_value(value, limit=MAX_VALUE_SIZE):
    value = bytes(value)
    if len(value) > limit:
        raise err("value_too_large")
    return value


class TransactionOptions:
    def __init__(self, tr):
        self._tr = tr

    def set_read_your_writes_disable(self):
        self._tr._ryw_disabled = True

    def set_snapshot_ryw_disable(self):
        self._tr._snapshot_ryw = False

    def set_next_write_no_write_conflict_range(self):
        self._tr._next_write_no_conflict = True

    def set_report_conflicting_keys(self):
        self._tr._report_conflicting_keys = True

    def set_lock_aware(self):
        """Ref: LOCK_AWARE — commit even while the database is locked."""
        self._tr._lock_aware = True

    def set_tag(self, tag):
        """Attach a transaction tag for per-tag throttling (ref:
        TAG/AUTO_THROTTLE_TAG options + TagThrottler): the ratekeeper
        samples per-tag load and can rate-limit a busy tag (error 1213,
        retryable) without touching other traffic. At most 5 tags of
        ≤16 bytes each (the reference's limits)."""
        if isinstance(tag, bytes):
            # latin-1 is byte-bijective: distinct binary tags stay
            # distinct throttle buckets (utf-8/replace would collide)
            tag = tag.decode("latin-1")
        if len(tag.encode("latin-1", "replace")) > 16:
            raise err("invalid_option_value")
        if tag not in self._tr._tags:
            if len(self._tr._tags) >= 5:
                raise err("invalid_option_value")
            self._tr._tags.append(tag)

    def set_auto_throttle_tag(self, tag):
        """Ref: AUTO_THROTTLE_TAG — same tag semantics as set_tag, but
        the tag is additionally eligible for ratekeeper AUTO throttling
        (here every tag already is: the ratekeeper auto-throttle
        samples all tagged traffic, so this is an alias kept for API
        parity with the reference bindings)."""
        self.set_tag(tag)

    def set_retry_limit(self, n):
        self._tr._retry_limit = int(n)

    def set_max_retry_delay(self, seconds):
        self._tr._max_retry_delay = float(seconds)

    def set_timeout(self, ms):
        self._tr._timeout_s = ms / 1000.0

    def set_read_system_keys(self):
        pass  # system keyspace is readable in-process

    def set_access_system_keys(self):
        pass

    def set_idempotency_id(self, idempotency_id):
        """Ref: IDEMPOTENCY_ID — a client-chosen token (≤255 bytes) the
        proxy records atomically with the commit; a retry after 1021
        resolves to the original outcome instead of double-applying."""
        if not idempotency_id or len(idempotency_id) > 255:
            raise err("invalid_option_value")
        self._tr._idempotency_id = bytes(idempotency_id)

    def set_automatic_idempotency(self):
        """Ref: AUTOMATIC_IDEMPOTENCY — generate a random id at commit
        time (kept across the retry loop) so commit_unknown_result
        becomes exactly-once without the caller inventing tokens."""
        self._tr._auto_idempotency = True

    def set_transaction_repair(self):
        """Enable conflict repair for this transaction regardless of the
        ``txn_repair`` knob (txn/repair.py): on ``not_committed`` with
        conflicting-key info, re-read only the conflicting keys at the
        rejecting commit version and replay (or cache-seed) the retry
        instead of restarting cold."""
        if self._tr._repair is None:
            from foundationdb_tpu.txn.repair import RepairEngine

            self._tr._repair = RepairEngine()

    def set_trace(self):
        """Force this transaction's trace to be SAMPLED regardless of
        ``tracing_sample_rate`` (ref: the DEBUG_TRANSACTION_IDENTIFIER
        / LOG_TRANSACTION option pair; also reachable by writing
        ``\\xff\\xff/tracing/token``). Best set before the first
        operation; a late force still promotes the buffered spans at
        commit."""
        self._tr._trace_forced = True
        if self._tr._span is span_mod.NULL:
            # tracing looked off when the root was (not) created:
            # rebuild sampled on next use — nothing was recorded yet
            self._tr._span = None


class _Snapshot:
    """Snapshot-isolation view: reads add no read conflict ranges."""

    def __init__(self, tr):
        self._tr = tr

    def get(self, key):
        return self._tr.get(key, snapshot=True)

    def __getitem__(self, key):
        if isinstance(key, slice):
            return self._tr.get_range(key.start, key.stop, snapshot=True)
        return self._tr.get(key, snapshot=True)

    def get_range(self, begin, end, **kw):
        kw["snapshot"] = True
        return self._tr.get_range(begin, end, **kw)

    def get_key(self, selector):
        return self._tr.get_key(selector, snapshot=True)

    def get_range_startswith(self, prefix, **kw):
        kw["snapshot"] = True
        return self._tr.get_range_startswith(prefix, **kw)


class Transaction:
    def __init__(self, database):
        self.db = database
        self._reset()

    @property
    def _cluster(self):
        # resolved through the Database each use: after a simulated crash
        # swaps the cluster, in-flight transactions talk to the *new*
        # incarnation and get fenced (too_old) instead of silently
        # committing into a dead object graph
        return self.db._cluster

    def _reset(self):
        # settle any still-outstanding async reads FIRST (before their
        # finalize bookkeeping's targets are replaced below): an
        # abandoned future is cancelled retryably and its span/op-log
        # cleanup runs — reset can never strand a waiter (FL002)
        pending = getattr(self, "_pending_reads", None)
        if pending:
            for fut in pending:
                fut.cancel()
        self._pending_reads = []  # in-flight FutureValue/FutureRange
        knobs = self.db._knobs
        self._knobs = knobs  # cached: ~3 property hops per op otherwise
        self._read_version = None
        self._writes = WriteMap()
        self._mutation_log = []  # [Mutation] in sequence order
        self._read_conflicts = []  # [(begin, end)]
        self._write_conflicts = []
        self._committed_version = None
        self._versionstamp = None
        self._state = "active"  # active | committed | error
        self._ryw_disabled = False
        self._snapshot_ryw = True
        self._next_write_no_conflict = False
        self._report_conflicting_keys = False
        self._lock_aware = False
        self._idempotency_id = None
        self._auto_idempotency = False
        self._tags = []  # transaction tags (per-tag throttling)
        self._retry_limit = None
        self._max_retry_delay = knobs.max_retry_delay_s
        self._timeout_s = None
        # the unified retry-delay policy (utils/backoff.py — flow
        # Backoff parity, jitter off the "backoff-jitter" stream); the
        # OBJECT rides on_error's keep-tuple so growth survives resets
        self._backoff = Backoff(
            initial_s=knobs.initial_backoff_s,
            max_s=knobs.max_retry_delay_s,
            growth=knobs.backoff_growth,
        )
        self._retries = 0
        self._size = 0
        self._special_writes = []  # buffered \xff\xff management writes
        self._conflicting_ranges = None  # from a failed reporting commit
        self._watches_pending = []  # [(key, seen_value, Watch-placeholder)]
        # conflict repair (txn/repair.py): the op-log recorder (None =
        # repair off — every check below is one attribute test), the
        # verified read caches a repaired retry serves from, and the
        # replay/commit bookkeeping flags
        self._repair = None
        if getattr(knobs, "txn_repair", False):
            from foundationdb_tpu.txn.repair import RepairEngine

            self._repair = RepairEngine()
        self._repair_cache = None  # key -> value, proven at _read_version
        self._repair_range_cache = None  # (b,e,limit,rev) -> tuple(rows)
        self._repair_ready = False  # op log replayed: commit, skip the body
        self._repair_assisted = False  # this attempt rode a repair
        # distributed tracing (utils/span.py): the lazy root span (None
        # until the first traced op; NULL when unsampled or off), the
        # in-flight commit span, and the per-txn force-sample flag. The
        # unsampled path keeps NO stamps or objects (the ≤2% budget):
        # abort promotion reconstructs on the error path, slow-commit
        # promotion is the batcher's per-window record.
        self._span = None
        self._commit_span = None
        self._trace_forced = False
        # options/snapshot views are lazy: most transactions never touch
        # them, and two object constructions per txn is real hot-path cost
        self._options = None
        self._snapshot_view = None

    @property
    def options(self):
        o = self._options
        if o is None:
            o = self._options = TransactionOptions(self)
        return o

    @property
    def snapshot(self):
        s = self._snapshot_view
        if s is None:
            s = self._snapshot_view = _Snapshot(self)
        return s

    # ─────────────────────────── tracing ──────────────────────────────
    def _trace_span(self):
        """The lazy root span: NULL when tracing is off or the draw
        missed, an emitting span when the per-txn force (or the draw)
        hits. Created on the first traced operation so untraced
        transactions never touch the sampling stream. Unsampled txns
        under an ENABLED rate arm promotion in _build_commit_request
        with a single clock stamp — no span objects on the 99% path."""
        sp = self._span
        if sp is None:
            sp = self._span = span_mod.transaction_span(
                self._knobs.tracing_sample_rate,
                forced=self._trace_forced,
            )
        return sp

    # ─────────────────────────── versions ─────────────────────────────
    def get_read_version(self):
        if self._read_version is None:
            grv = self._cluster.grv_proxy
            sp = self._trace_span()
            if not sp.sampled:
                # NULL or deferred: per-op child spans only exist for
                # SAMPLED traces — the deferred (promotion) record is
                # root + commit, kept cheap enough for the ≤2% budget
                self._read_version = (
                    grv.get_read_version(tags=tuple(self._tags))
                    if self._tags else grv.get_read_version()
                )
                return self._read_version
            gsp = sp.child("txn.grv")
            # ambient context: an in-process GrvProxy (or the RPC
            # transport's tracing frame) parents its grant span here
            prior = span_mod.set_current(gsp.context())
            try:
                self._read_version = (
                    grv.get_read_version(tags=tuple(self._tags))
                    if self._tags else grv.get_read_version()
                )
            finally:
                span_mod.set_current(prior)
            gsp.finish(version=self._read_version)
        return self._read_version

    def set_read_version(self, version):
        self._read_version = int(version)

    def get_committed_version(self):
        if self._committed_version is None:
            raise err("no_commit_version")
        return self._committed_version

    def get_versionstamp(self):
        """Returns a callable resolving to the txn's 10-byte versionstamp
        after commit (the binding returns a future; call it post-commit)."""
        return lambda: self._require_versionstamp()

    def _require_versionstamp(self):
        if self._versionstamp is None:
            raise err("no_commit_version")
        return self._versionstamp

    # ───────────────────────────── reads ──────────────────────────────
    def _guard(self):
        if self._state in ("committed", "committing"):
            raise err("used_during_commit")
        if self._state == "cancelled":
            raise err("transaction_cancelled")

    @staticmethod
    def _settled(value=None, error=None, cls=FutureValue, finalize=None):
        """An already-resolved future (special-space rows, RYW-complete
        lookups, in-process storage): constructed and settled in one
        place so every return path hands back the same surface."""
        fut = cls(finalize=finalize)
        if error is not None:
            fut.set_exception(error)
        else:
            fut.set(value)
        return fut

    def _read_future(self, key, rv, snapshot, fold_entry=None):
        """One storage point read as a future. A repaired retry serves
        it from the verified cache (txn/repair.py) — resolver-proven
        equal to storage at ``rv`` — and settles immediately. Otherwise
        the read rides the cluster's async path (the connection's
        ReadBatcher — rpc/service.py) when it has one, or resolves
        inline against in-process storage. The finalize callback runs
        once on the consuming ``wait()``: span finish, repair op-log
        record, read-conflict range, RYW fold — the same per-key
        bookkeeping the synchronous path always did."""
        writes = self._writes if fold_entry is not None else None
        cache = self._repair_cache
        if cache is not None and key in cache:
            val = cache[key]
            eng = self._repair
            if eng is not None and not snapshot \
                    and key not in eng.point_reads:
                eng.point_reads[key] = val
            if not snapshot:
                self._add_read_conflict(key, key_successor(key))
            if writes is not None:
                val = writes.fold(fold_entry, val)
            return self._settled(val)
        sp = self._span
        rsp = ctx = None
        if sp is not None and sp.sampled:
            rsp = sp.child("txn.read")
            ctx = rsp.context()

        def finalize(val, error):
            if rsp is not None:
                rsp.finish()
            if error is not None:
                return None
            eng = self._repair
            if eng is not None and not snapshot \
                    and key not in eng.point_reads:
                eng.point_reads[key] = val
            if not snapshot:
                self._add_read_conflict(key, key_successor(key))
            return writes.fold(fold_entry, val) \
                if writes is not None else val

        st = self._cluster.read_storage(key)
        get_async = getattr(st, "get_async", None)
        if get_async is not None:
            fut = get_async(key, rv, finalize=finalize, ctx=ctx)
        else:
            # in-process storage tier: resolve now, defer bookkeeping
            # to the consuming wait() exactly like the batched path
            prior = span_mod.set_current(ctx)
            try:
                val, e = st.get(key, rv), None
            except FDBError as exc:
                val, e = None, exc
            finally:
                span_mod.set_current(prior)
            fut = self._settled(val, error=e, finalize=finalize)
        self._pending_reads.append(fut)
        return fut

    def get_async(self, key, snapshot=False):
        """Future-returning point read (ref: Transaction::get returns
        Future<Optional<Value>>); :meth:`get` is ``.wait()`` over the
        same machinery, so one code path serves both forms."""
        self._guard()
        key = _check_key(key)
        if key.startswith(b"\xff") and specialkeys.contains(key):
            if self._repair is not None:
                # virtual-module rows aren't verifiable at a later
                # version: this op log never auto-replays
                self._repair.unreplayable = True
            try:
                val = specialkeys.get(self, key)
            except FDBError as e:
                return self._settled(error=e)
            return self._settled(val)
        rv = self.get_read_version()
        if not self._ryw_disabled:
            known, needs_base, entry = self._writes.lookup(key)
            if known:
                if not needs_base:
                    return self._settled(self._writes.fold(entry, None))
                return self._read_future(key, rv, snapshot,
                                         fold_entry=entry)
        return self._read_future(key, rv, snapshot)

    def get(self, key, snapshot=False):
        return self.get_async(key, snapshot=snapshot).wait()

    def get_key_async(self, selector, snapshot=False):
        """Future-returning key-selector resolution."""
        self._guard()
        if specialkeys.contains(getattr(selector, "key", None)):
            # selector resolution is not defined over the virtual special
            # space (module rows are materialized, not stored)
            raise err("key_outside_legal_range")
        rv = self.get_read_version()
        if self._repair is not None:
            # selector resolution isn't recorded key-by-key, so it
            # can't be re-verified at the repair version: fall back to
            # the seeded rerun, never the verbatim replay
            self._repair.unreplayable = True

        def finalize(k, error):
            if error is not None:
                return None
            if not snapshot and k not in (b"", b"\xff"):
                self._add_read_conflict(k, key_successor(k))
            return k

        st = self._cluster.read_storage()
        resolve_async = getattr(st, "resolve_selector_async", None)
        if resolve_async is not None:
            fut = resolve_async(selector, rv, finalize=finalize)
        else:
            try:
                k, e = st.resolve_selector(selector, rv), None
            except FDBError as exc:
                k, e = None, exc
            fut = self._settled(k, error=e, finalize=finalize)
        self._pending_reads.append(fut)
        return fut

    def get_key(self, selector, snapshot=False):
        return self.get_key_async(selector, snapshot=snapshot).wait()

    def get_range_async(self, begin, end, limit=0, reverse=False,
                        snapshot=False, streaming_mode=None):
        """Future-returning merged range read: snapshot rows overlaid
        with this txn's writes. begin/end: bytes or KeySelector
        (selectors resolve synchronously at issue — rare, and a
        selector walk cannot ride a key-bounded batch). The RYW
        overlay is captured AT ISSUE TIME, so the result reflects the
        writes present when the read was issued — the reference's
        future semantics."""
        self._guard()
        if specialkeys.contains(begin) or (
            isinstance(begin, KeySelector) and specialkeys.contains(begin.key)
        ):
            # special-space ranges take literal bytes only (the reference
            # rejects selectors against most special-key modules too)
            if not specialkeys.contains(begin) or not isinstance(end, bytes):
                raise err("key_outside_legal_range")
            if self._repair is not None:
                self._repair.unreplayable = True
            try:
                rows = specialkeys.get_range(
                    self, begin, min(end, specialkeys.END),
                    limit=limit, reverse=reverse,
                )
            except FDBError as e:
                return self._settled(error=e, cls=FutureRange)
            return self._settled(rows, cls=FutureRange)
        rv = self.get_read_version()
        st = self._cluster.read_storage()
        if begin is None:
            begin = b""
        if end is None:
            end = b"\xff"
        b = begin if isinstance(begin, bytes) else st.resolve_selector(begin, rv)
        e = end if isinstance(end, bytes) else st.resolve_selector(end, rv)
        if b > e:
            raise err("inverted_range")

        overlaps = not self._ryw_disabled and (
            self._writes.cleared_in(b, e)
            or next(self._writes.overlay_range(b, e), None) is not None
        )
        if overlaps:
            # merge path: fetch the whole base range, overlay at wait()
            # (cleared/overlay snapshots taken NOW — issue-time RYW)
            cleared = list(self._writes.cleared_in(b, e))
            overlay = list(self._writes.overlay_range(b, e))
            req_limit, req_reverse = 0, False
        else:
            # fast path: no uncommitted writes in range — push
            # limit/reverse down to storage instead of materializing
            cleared = overlay = None
            req_limit, req_reverse = limit, reverse
        sig = (b, e, req_limit, req_reverse)
        writes = self._writes

        def postprocess(rows):
            if overlay is None:
                return rows
            d = dict(rows)
            for cb, ce in cleared:
                for k in [k for k in d if cb <= k < ce]:
                    del d[k]
            for k, entry in overlay:
                base = d.get(k) if not entry.independent else None
                v = writes.fold(entry, base)
                if v is None:
                    d.pop(k, None)
                else:
                    d[k] = v
            out = sorted(d.items(), reverse=reverse)
            if limit:
                out = out[:limit]
            return out

        def record_conflict(out):
            if snapshot:
                return
            # conflict range covers what was actually observed
            if limit and out:
                hi = key_successor(out[-1][0]) if not reverse else e
                lo = b if not reverse else out[-1][0]
                self._add_read_conflict(lo, hi)
            else:
                self._add_read_conflict(b, e)

        rcache = self._repair_range_cache
        if rcache is not None and sig in rcache:
            rows = list(rcache[sig])
            eng = self._repair
            if eng is not None and not snapshot \
                    and sig not in eng.range_reads:
                eng.range_reads[sig] = tuple(rows)
            out = postprocess(rows)
            record_conflict(out)
            return self._settled(out, cls=FutureRange)
        sp = self._span
        rsp = ctx = None
        if sp is not None and sp.sampled:
            rsp = sp.child("txn.read_range")
            ctx = rsp.context()

        def finalize(rows, error):
            if rsp is not None:
                rsp.finish()
            if error is not None:
                return None
            eng = self._repair
            if eng is not None and not snapshot \
                    and sig not in eng.range_reads:
                eng.range_reads[sig] = tuple(rows)
            out = postprocess(rows)
            record_conflict(out)
            return out

        range_async = getattr(st, "get_range_async", None)
        if range_async is not None:
            fut = range_async(b, e, rv, limit=req_limit,
                              reverse=req_reverse, finalize=finalize,
                              ctx=ctx)
        else:
            prior = span_mod.set_current(ctx)
            try:
                rows, exc = st.get_range(
                    b, e, rv, limit=req_limit, reverse=req_reverse
                ), None
            except FDBError as x:
                rows, exc = None, x
            finally:
                span_mod.set_current(prior)
            fut = self._settled(rows, error=exc, cls=FutureRange,
                                finalize=finalize)
        self._pending_reads.append(fut)
        return fut

    def get_range(self, begin, end, limit=0, reverse=False, snapshot=False,
                  streaming_mode=None):
        """Merged range read: snapshot rows overlaid with this txn's writes.

        begin/end: bytes or KeySelector. Returns list[(key, value)].
        """
        return self.get_range_async(
            begin, end, limit=limit, reverse=reverse, snapshot=snapshot,
            streaming_mode=streaming_mode,
        ).wait()

    def get_range_startswith_async(self, prefix, **kw):
        prefix = bytes(prefix)
        return self.get_range_async(prefix, strinc(prefix), **kw)

    def get_range_startswith(self, prefix, **kw):
        prefix = bytes(prefix)
        return self.get_range(prefix, strinc(prefix), **kw)

    # ───────────────────────────── writes ─────────────────────────────
    def _add_read_conflict(self, begin, end):
        self._read_conflicts.append((begin, end))

    def _add_write_conflict(self, begin, end):
        if self._next_write_no_conflict:
            self._next_write_no_conflict = False
            return
        self._write_conflicts.append((begin, end))

    def add_read_conflict_range(self, begin, end):
        self._guard()
        self._read_conflicts.append((bytes(begin), bytes(end)))

    def add_read_conflict_key(self, key):
        self.add_read_conflict_range(key, key_successor(key))

    def add_write_conflict_range(self, begin, end):
        self._guard()
        self._write_conflicts.append((bytes(begin), bytes(end)))

    def add_write_conflict_key(self, key):
        self.add_write_conflict_range(key, key_successor(key))

    def _log_mutation(self, m):
        self._mutation_log.append(m)
        self._size += len(m.key) + len(m.param or b"")
        if self._size > self._knobs.transaction_size_limit:
            raise err("transaction_too_large")

    def set(self, key, value):
        # the hottest client call: helpers (_log_mutation,
        # _add_write_conflict, key_successor) are inlined — at tens of
        # thousands of commits/sec their call overhead was measurable
        self._guard()
        # limits come from the knobs (defaults mirror core.keys
        # constants) so key_size_limit / value_size_limit are tunable
        key = _check_key(key, self._knobs.key_size_limit)
        value = _check_value(value, self._knobs.value_size_limit)
        if key.startswith(b"\xff") and specialkeys.contains(key):
            specialkeys.write(self, key, value)
            return
        self._writes.set(key, value)
        self._mutation_log.append(Mutation(Op.SET, key, value))
        self._size += len(key) + len(value)
        if self._size > self._knobs.transaction_size_limit:
            raise err("transaction_too_large")
        if self._next_write_no_conflict:
            self._next_write_no_conflict = False
        else:
            self._write_conflicts.append((key, key + b"\x00"))

    def clear(self, key):
        self._guard()
        key = _check_key(key)
        if specialkeys.contains(key):
            specialkeys.clear(self, key)
            return
        self._writes.clear(key)
        self._log_mutation(Mutation(Op.CLEAR_RANGE, key, key_successor(key)))
        self._add_write_conflict(key, key_successor(key))

    def clear_range(self, begin, end):
        self._guard()
        begin, end = _check_key(begin), _check_key(end)
        if begin > end:
            raise err("inverted_range")
        if specialkeys.contains(begin):
            specialkeys.clear_range(self, begin, end)
            return
        self._writes.clear_range(begin, end)
        self._log_mutation(Mutation(Op.CLEAR_RANGE, begin, end))
        self._add_write_conflict(begin, end)

    def clear_range_startswith(self, prefix):
        prefix = bytes(prefix)
        self.clear_range(prefix, strinc(prefix))

    def _atomic(self, op, key, param):
        self._guard()
        key = _check_key(key)
        if specialkeys.contains(key):
            # management modules take set/clear only; an atomic would
            # smuggle a raw mutation into the virtual keyspace
            raise err("key_outside_legal_range")
        param = bytes(param)
        self._writes.atomic(op, key, param)
        self._log_mutation(Mutation(op, key, param))
        self._add_write_conflict(key, key_successor(key))

    def add(self, key, param):
        self._atomic(Op.ADD, key, param)

    def bit_and(self, key, param):
        self._atomic(Op.BIT_AND, key, param)

    def bit_or(self, key, param):
        self._atomic(Op.BIT_OR, key, param)

    def bit_xor(self, key, param):
        self._atomic(Op.BIT_XOR, key, param)

    def min(self, key, param):
        self._atomic(Op.MIN, key, param)

    def max(self, key, param):
        self._atomic(Op.MAX, key, param)

    def byte_min(self, key, param):
        self._atomic(Op.BYTE_MIN, key, param)

    def byte_max(self, key, param):
        self._atomic(Op.BYTE_MAX, key, param)

    def append_if_fits(self, key, param):
        self._atomic(Op.APPEND_IF_FITS, key, param)

    def compare_and_clear(self, key, param):
        self._atomic(Op.COMPARE_AND_CLEAR, key, param)

    def set_versionstamped_key(self, key, value):
        self._guard()
        self._log_mutation(Mutation(Op.SET_VERSIONSTAMPED_KEY, key, value))
        # write conflict on the placeholder-resolved key is unknowable now;
        # the reference adds it server-side. Conservatively skip (matches
        # the binding: versionstamped keys are unique, conflicts moot).

    def set_versionstamped_value(self, key, value):
        self._guard()
        key = _check_key(key)
        self._log_mutation(Mutation(Op.SET_VERSIONSTAMPED_VALUE, key, value))
        self._add_write_conflict(key, key_successor(key))

    # dict-style sugar (binding parity)
    def __getitem__(self, key):
        if isinstance(key, slice):
            return self.get_range(key.start, key.stop)
        return self.get(key)

    def __setitem__(self, key, value):
        self.set(key, value)

    def __delitem__(self, key):
        if isinstance(key, slice):
            self.clear_range(key.start, key.stop)
        else:
            self.clear(key)

    # ───────────────────── size/split estimation ──────────────────────
    def get_estimated_range_size_bytes(self, begin, end):
        """Ref: fdb_transaction_get_estimated_range_size_bytes (sampled
        storage metrics — an estimate, not an exact byte count)."""
        self._guard()
        if self._repair is not None:
            self._repair.unreplayable = True  # sampled, not re-verifiable
        return self._cluster.estimated_range_size_bytes(
            _check_key(begin), _check_key(end)
        )

    def get_range_split_points(self, begin, end, chunk_size):
        """Ref: fdb_transaction_get_range_split_points — boundary keys
        cutting [begin, end) into ~chunk_size-byte chunks (includes both
        endpoints)."""
        self._guard()
        if self._repair is not None:
            self._repair.unreplayable = True
        return self._cluster.range_split_points(
            _check_key(begin), _check_key(end), int(chunk_size)
        )

    def get_approximate_size(self):
        """Ref: fdb_transaction_get_approximate_size — the commit
        payload this transaction has accumulated so far."""
        self._guard()
        return self._size

    # ─────────────────────────── watches ──────────────────────────────
    def watch(self, key):
        """Register interest in key changes; activates at commit.

        Ref: Transaction::watch — the watch compares against the value as
        of this transaction and fires when it changes afterward."""
        self._guard()
        key = _check_key(key)
        seen = self.get(key, snapshot=True)
        handle = _WatchHandle(key, seen)
        self._watches_pending.append(handle)
        return handle

    # ─────────────────────────── commit ───────────────────────────────
    def _drain_reads(self):
        """Settle every still-outstanding async read before the commit
        request is built: drained reads add their conflict ranges (an
        unwaited ``get_async`` the app ignored still participates in
        OCC, matching the reference where the read future's storage
        reply registered the range regardless of the app consuming
        it). Per-key read errors stay with their futures — an app that
        caught (or ignored) a failed read can still commit what it has."""
        pending, self._pending_reads = self._pending_reads, []
        for fut in pending:
            try:
                fut.wait()
            except FDBError:
                pass

    def _build_commit_request(self):
        self._drain_reads()
        # Lazy read version for READ-FREE transactions: with no read
        # conflict ranges the resolver never compares anything against
        # rv — it only places the txn inside the MVCC window — so the
        # PROXY assigns its current committed version at batch time
        # (read_version=None on the wire). Write-only traffic thus
        # skips the GRV round trip entirely: over a remote transport
        # that round trip was the single largest per-txn cost. A txn
        # that ever read (or pinned a version) keeps its honest rv, and
        # TAGGED txns always pay the GRV — per-tag throttling is
        # enforced at that gate (skipping it would let a throttled tag
        # write unthrottled); the untagged global budget is enforced at
        # the proxy for rv-None requests instead.
        idmp = self._ensure_idempotency_id()
        if (self._read_version is None and not self._read_conflicts
                and not self._tags and idmp is None):
            # id-carrying txns never ride the lazy-rv fast path: the
            # OCC serialization of a 1021 retry against its own
            # original (the idmp-row conflict ranges the proxy declares
            # in _build_txns) needs an honest read version — a
            # proxy-assigned rv on a different fleet member could land
            # at-or-after the original's commit and miss the conflict
            # (ADVICE r5: the read-free retry double-apply race)
            rv = None
        else:
            rv = self.get_read_version()
        rcr = _coalesce(self._read_conflicts)
        wcr = _coalesce(self._write_conflicts)
        # columnar fast path (core/flatpack.py): pre-encode the conflict
        # ranges into limb-entry blobs HERE, on the client, so neither
        # the wire decode nor the proxy's batch build ever re-parses a
        # key. Pure bytes ops — the limb encoding of an in-capacity key
        # is its zero-padded bytes plus a length word. None (a key past
        # limb capacity) simply leaves the request on the legacy path.
        flat = None
        if getattr(self._knobs, "commit_pack_path", "legacy") == "flat":
            flat = flatpack.encode_conflicts(
                rcr, wcr, self._knobs.key_limbs
            )
        # commit span (submit → settle): its context rides the request —
        # the server batch/stage spans parent to it
        sctx = None
        sp = self._trace_span()
        if sp is not span_mod.NULL:
            csp = self._commit_span = sp.child(
                "txn.commit", mutations=len(self._mutation_log))
            sctx = csp.context()
        return CommitRequest(
            read_version=rv,
            mutations=list(self._mutation_log),
            read_conflict_ranges=rcr,
            write_conflict_ranges=wcr,
            # the repair engine needs the conflicting ranges AND the
            # rejecting commit version on every 1020 it might repair
            report_conflicting_keys=(self._report_conflicting_keys
                                     or self._repair is not None),
            lock_aware=self._lock_aware,
            idempotency_id=idmp,
            flat_conflicts=flat,
            span_context=sctx,
            tags=tuple(self._tags),
        )

    def _ensure_idempotency_id(self):
        if self._idempotency_id is None and self._auto_idempotency:
            from foundationdb_tpu.core import deterministic

            # injected entropy: a seeded sim mints the same ids every
            # run, so 1021-retry histories replay byte-identically
            self._idempotency_id = deterministic.token_bytes(
                16, name="idempotency-id"
            )
        return self._idempotency_id

    def _finish_commit(self, result):
        """Mixed data+management transactions are NOT atomic: the data
        commit becomes durable first, then the buffered special-key
        writes apply. ``commit()`` re-checks the lock up front so a
        locked database rejects the whole transaction before any data
        commits; if a lock races in between the two halves, the data
        commit stands (it passed the proxy's lock check) and the fenced
        management writes are dropped with a trace — they are exactly
        the writes the new lock exists to fence, and raising here would
        falsely report a durably-committed transaction as failed."""
        if isinstance(result, FDBError):
            if result.code == 1021 and self._idempotency_id is not None:
                # commit_unknown_result disambiguation (ref:
                # IdempotencyId.actor.cpp): the id row is written
                # atomically WITH the mutations, so its presence at a
                # fresh read version proves the commit applied — resolve
                # to the original outcome instead of surfacing 1021
                recovered = self._lookup_idempotency()
                if recovered is not None:
                    result = recovered
            if isinstance(result, FDBError):
                self._state = "error"
                # conflict reporting: the failed txn's conflicting read
                # ranges become readable at
                # \xff\xff/transaction/conflicting_keys/ until the next
                # reset (ref: SpecialKeySpace ConflictingKeys)
                self._conflicting_ranges = getattr(
                    result, "conflicting_key_ranges", None
                )
                self._trace_commit_done(result)
                raise result
        # the data half is durable regardless of what the management
        # half does below: record it first so the client can always
        # observe what committed (mixed transactions are not atomic)
        if self._repair_assisted:
            # a repaired retry made it durable: the goodput the engine
            # exists for (txn/repair.py; rides the proxy registry)
            from foundationdb_tpu.txn import repair as repair_mod

            repair_mod.note(self._cluster, "repair_commits")
            self._repair_assisted = False
        self._committed_version = result
        self._versionstamp = Versionstamp.from_version(result).tr_version
        self._trace_commit_done(None)
        try:
            specialkeys.commit_special(self)
        except FDBError as e:
            if e.description == "database_locked" and not self._lock_aware:
                from foundationdb_tpu.utils.trace import TraceEvent

                TraceEvent("ManagementWritesFencedByLock",
                           severity=30).detail(
                    committed_version=result).log()
            else:
                # a genuine management failure (a lock-AWARE txn is
                # never fenced by the lock — e.g. locking over another
                # operator's uid raises its own 1038): surface it
                self._state = "error"
                raise
        self._state = "committed"
        self._activate_watches()

    def _trace_commit_done(self, error):
        """Settle the transaction's trace. Sampled: finish the commit
        span and the root. Unsampled-but-enabled: the ABORT promotion
        gate — a commit that failed (or was force-traced too late to
        re-root) reconstructs and emits its record on the error path;
        the happy path keeps nothing (slow-commit promotion is the
        batcher's per-window ``commit.window`` record instead — the
        per-txn clock stamps this once took busted the ≤2% budget)."""
        root = self._span
        if root is None:
            return
        if root is span_mod.NULL:
            if ((error is not None or self._trace_forced)
                    and self._knobs.tracing_sample_rate > 0.0):
                end = span_mod.now()
                span_mod.promote_lite(
                    end, end, commit_begin=end,
                    error_code=None if error is None else error.code,
                    retries=self._retries,
                )
            self._span = None
            return
        csp = self._commit_span
        if csp is not None:
            if error is not None:
                csp.finish(status="error", error_code=error.code)
            else:
                csp.finish(status="committed",
                           version=self._committed_version)
            self._commit_span = None
        root.finish(
            status="error" if error is not None else "committed",
            retries=self._retries,
        )
        self._span = None  # settled: a reused handle restarts its trace

    def _lookup_idempotency(self):
        """Best-effort id-row check at a fresh read version: the commit
        version if the id committed, else None. A cluster mid-recovery
        can fail the check — the 1021 then stands and the retry loop
        resubmits the SAME id, where the proxy's dedupe (the
        authoritative check, serialized with every commit) resolves it."""
        from foundationdb_tpu.core import systemdata

        try:
            rv = self._cluster.grv_proxy.get_read_version(
                priority="immediate"
            )
            key = systemdata.idmp_key(self._idempotency_id)
            row = self._cluster.read_storage(key).get(key, rv)
        except Exception:
            return None
        return None if row is None else systemdata.unpack_version(row)

    def _precheck_special_lock(self):
        """A mixed data+management transaction checks the lock BEFORE the
        data commit: without this, a lock landing between the (durable)
        data commit and the management application would surface as a
        non-retryable database_locked on a transaction whose data already
        committed (see _finish_commit for the remaining race)."""
        if self._special_writes and not self._lock_aware \
                and self._cluster.lock_uid() is not None:
            raise err("database_locked")

    @property
    def repair_ready(self):
        """True when a conflict repair replayed this transaction's op
        log verbatim (txn/repair.py): the retry loop should resubmit —
        ``commit()`` / ``commit_async()`` — WITHOUT re-running the
        body; running it anyway would double-apply the restored
        mutations."""
        return self._repair_ready

    def try_repair(self, error):
        """Attempt conflict repair for a failed commit instead of the
        cold restart (txn/repair.py). True = repaired: the read version
        moved to the rejecting commit version, reads are verified or
        refreshed, no backoff is owed — retry immediately (checking
        :attr:`repair_ready` first). False = restart cold (the caller
        owns reset/backoff). ``on_error`` calls this automatically."""
        if not isinstance(error, FDBError):
            return False
        from foundationdb_tpu.txn import repair as repair_mod

        return repair_mod.attempt(self, error)

    def commit(self):
        self._guard()
        self._repair_ready = False  # consumed: this IS the resubmission
        self._drain_reads()
        if not self._mutation_log and not self._write_conflicts:
            # read-only (or management-only): nothing to resolve
            # (ref: read-only commits skip proxies)
            specialkeys.commit_special(self)
            self._state = "committed"
            self._activate_watches()
            self._trace_commit_done(None)
            return
        self._precheck_special_lock()
        self._finish_commit(
            self._cluster.commit_proxy.commit(self._build_commit_request())
        )

    def commit_async(self):
        """Submit to the batching commit proxy; returns a CommitFuture.

        The cooperative-actor commit path (ref: Transaction::commit is an
        ACTOR returning Future<Void>): the caller yields until
        ``fut.done()``, then calls :meth:`commit_finish` to apply the
        outcome. Requires the cluster's proxy to support ``submit``
        (BatchingCommitProxy); the plain synchronous proxy does not.
        """
        self._guard()
        self._repair_ready = False  # consumed: this IS the resubmission
        self._drain_reads()
        if not self._mutation_log and not self._write_conflicts:
            from foundationdb_tpu.server.batcher import CommitFuture

            # same contract as commit()'s read-only path: management-only
            # transactions still apply their buffered special writes
            specialkeys.commit_special(self)
            self._state = "committed"
            self._activate_watches()
            self._trace_commit_done(None)
            fut = CommitFuture()
            fut.set(None)
            return fut
        self._precheck_special_lock()
        req = self._build_commit_request()
        # in-flight: further ops (or a second commit) must fail
        # used_during_commit, not silently re-submit the mutation log
        # (ref: used_during_commit in NativeAPI while the commit actor runs)
        self._state = "committing"
        return self._cluster.commit_proxy.submit(req)

    def commit_finish(self, fut):
        """Apply a resolved commit_async future (raises FDBError on
        conflict, exactly like commit())."""
        if self._state == "committed":  # read-only fast path already done
            return
        self._finish_commit(fut.result(timeout=0))

    def _activate_watches(self):
        for h in self._watches_pending:
            h._bind(self._cluster.read_storage(h.key).watch(h.key, h.seen_value))
        self._watches_pending = []

    def on_error(self, error):
        """The retry protocol (ref: Transaction::onError): backoff and
        reset for retryable errors, re-raise otherwise."""
        if not isinstance(error, FDBError) or not error.is_retryable:
            raise error
        self._retries += 1
        if self._retry_limit is not None and self._retries > self._retry_limit:
            raise error
        if self.try_repair(error):
            # repaired (txn/repair.py): read version moved to the
            # rejecting commit version, reads verified or refreshed —
            # no backoff owed, retry immediately (repair_ready decides
            # whether the body re-runs)
            return
        # set_max_retry_delay may have moved the cap after _reset built
        # the policy: the option always wins (reference binding parity)
        self._backoff.max_s = self._max_retry_delay
        self._backoff.sleep()
        # timeout/retry_limit/max_retry_delay persist across resets, like
        # the reference binding (fdb_transaction_reset keeps those
        # options); the idempotency id persists too — the SAME id must
        # ride every retry of this logical transaction or the proxy's
        # dedupe has nothing to match (ref: IdempotencyId surviving
        # onError)
        keep = (self._retries, self._backoff, self._retry_limit,
                self._max_retry_delay, self._timeout_s,
                self._idempotency_id, self._auto_idempotency,
                self._trace_forced, self._tags)
        self._reset()
        (self._retries, self._backoff, self._retry_limit,
         self._max_retry_delay, self._timeout_s,
         self._idempotency_id, self._auto_idempotency,
         self._trace_forced, self._tags) = keep

    def reset(self):
        self._reset()

    def cancel(self):
        """Ref: fdb_transaction_cancel — all further use raises 1025
        until reset()."""
        self._state = "cancelled"
        # outstanding async reads settle with 1025 NOW (FL002): a
        # waiter blocked on a cancelled txn's read must not hang
        pending, self._pending_reads = self._pending_reads, []
        for fut in pending:
            fut.cancel()


class _WatchHandle:
    """Client-side watch future (ref: Watch in NativeAPI)."""

    def __init__(self, key, seen_value):
        self.key = key
        self.seen_value = seen_value
        self._watch = None

    def _bind(self, storage_watch):
        self._watch = storage_watch

    @property
    def active(self):
        return self._watch is not None

    def is_set(self):
        return self._watch is not None and self._watch.fired

    def wait(self, timeout=None, poll=0.001):
        """Block until fired (in-process: commits fire synchronously;
        remote: a blocking server-side wait instead of poll RPCs)."""
        if self._watch is None:
            raise err("operation_failed")
        waiter = getattr(self._watch, "wait_remote", None)
        if waiter is not None:
            if waiter(timeout):
                return True
            raise err("timed_out")
        start = time.monotonic()
        # jittered growing poll (utils/backoff.py): a long-parked watch
        # costs ~50 wakeups/s at first, decaying to ~50/s-worst-case
        # 20ms polls — not a 1ms busy spin for its whole life
        poller = Backoff(initial_s=poll, max_s=0.02, growth=1.5)
        while not self._watch.fired:
            if timeout is not None and time.monotonic() - start > timeout:
                raise err("timed_out")
            poller.sleep()
        return True


def _coalesce(ranges):
    """Sort + merge overlapping conflict ranges (smaller resolver
    payload). 0/1-range transactions — the bulk of point traffic —
    skip the sort entirely."""
    if len(ranges) <= 1:
        return list(ranges)
    rs = sorted(ranges)
    out = [list(rs[0])]
    for b, e in rs[1:]:
        if b <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([b, e])
    return [(b, e) for b, e in out]
