"""Write map: a transaction's uncommitted writes, for read-your-writes.

Ref parity: the WriteMap inside fdbclient/ReadYourWrites.actor.cpp /
RYWIterator — tracks sets, clears (point + range), and pending atomic op
chains in sequence order, and answers "what would this key/range look
like if my writes were applied over the snapshot".
"""

from foundationdb_tpu.core.mutations import Op, apply_atomic


class _Entry:
    __slots__ = ("seq", "ops", "base_cleared")

    def __init__(self, seq, ops, base_cleared):
        self.seq = seq
        self.ops = ops  # list[(Op, param)], applied in order over base
        self.base_cleared = base_cleared

    @property
    def independent(self):
        """True if the chain's result doesn't depend on the snapshot value."""
        return self.base_cleared or (self.ops and self.ops[0][0] is Op.SET)


class WriteMap:
    def __init__(self):
        # plain dict: transactions write a handful of keys, and the only
        # ordered consumers (clear_range shadowing, overlay_range merges)
        # sort on demand — measurably cheaper per-transaction than a
        # SortedDict, which costs ~30us just to construct (the commit
        # pipeline creates one WriteMap per txn at >100k txns/sec)
        self._writes = {}  # key -> _Entry
        self._clears = []  # [(seq, begin, end)]
        self._seq = 0

    def _keys_in(self, begin, end):
        return sorted(k for k in self._writes if begin <= k < end)

    def _next_seq(self):
        self._seq += 1
        return self._seq

    def _covered_by_clear(self, key):
        return any(b <= key < e for _, b, e in self._clears)

    # ───────────────────────── mutations ──────────────────────────────
    def set(self, key, value):
        seq = self._next_seq()
        self._writes[key] = _Entry(seq, [(Op.SET, value)], base_cleared=False)
        return seq

    def clear(self, key):
        seq = self._next_seq()
        self._writes[key] = _Entry(seq, [(Op.CLEAR, None)], base_cleared=True)
        return seq

    def clear_range(self, begin, end):
        seq = self._next_seq()
        self._clears.append((seq, begin, end))
        for k in self._keys_in(begin, end):
            self._writes[k] = _Entry(seq, [(Op.CLEAR, None)], base_cleared=True)
        return seq

    def atomic(self, op, key, param):
        seq = self._next_seq()
        entry = self._writes.get(key)
        if entry is None:
            entry = _Entry(seq, [], base_cleared=self._covered_by_clear(key))
            self._writes[key] = entry
        entry.seq = seq
        entry.ops.append((op, param))
        return seq

    # ─────────────────────────── reads ────────────────────────────────
    def lookup(self, key):
        """→ (known, needs_base, entry_or_None).

        known=True: this map fully determines the value (maybe via a base
        read — needs_base says whether the caller must supply the
        snapshot value to fold the atomic chain)."""
        e = self._writes.get(key)
        if e is not None:
            return True, not e.independent, e
        if self._covered_by_clear(key):
            return True, False, None
        return False, False, None

    def fold(self, entry, base):
        if entry is None:
            return None
        val = None if entry.base_cleared else base
        for op, param in entry.ops:
            val = apply_atomic(op, val, param)
        return val

    def overlay_range(self, begin, end):
        """Iterate written keys in [begin, end) → (key, entry)."""
        for k in self._keys_in(begin, end):
            yield k, self._writes[k]

    def cleared_in(self, begin, end):
        """Clear ranges intersecting [begin, end)."""
        return [(b, e) for _, b, e in self._clears if b < end and begin < e]

    def is_cleared(self, key, after_seq=0):
        return any(b <= key < e and s > after_seq for s, b, e in self._clears)

    @property
    def empty(self):
        return not self._writes and not self._clears
