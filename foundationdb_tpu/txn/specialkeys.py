"""The special key space: \\xff\\xff/... module registry.

Ref parity: fdbclient/SpecialKeySpace.actor.cpp — keys above \\xff\\xff
are not stored rows but views and management handles materialized by the
client at read time:

- ``\\xff\\xff/status/json``                 → cluster status as JSON bytes
- ``\\xff\\xff/connection_string``           → how this client reached the
  cluster (remote: the cluster-file body; in-process: ``local``)
- ``\\xff\\xff/transaction/conflicting_keys/<begin>`` → after a commit
  failed 1020 with ``options.set_report_conflicting_keys()``, boundary
  rows ("1" opens a conflicting range, "0" closes it — the reference's
  exact encoding)
- ``\\xff\\xff/management/excluded/<id>``    → storage exclusion: ``set``
  begins draining the storage at commit, ``clear`` re-includes it, range
  reads list current exclusions (ref: excludedServersSpecialKeyRange)

Reads of special keys take no read-conflict ranges and never touch
storage. Management writes are buffered on the transaction and applied
at commit time, like the reference's special-key commit path.
"""

import json

from foundationdb_tpu.core.errors import err

PREFIX = b"\xff\xff"
END = b"\xff\xff\xff"


def contains(key):
    """True iff ``key`` (bytes) lies in the special space [PREFIX, END)."""
    return isinstance(key, bytes) and key.startswith(PREFIX) and key < END

STATUS_JSON = b"\xff\xff/status/json"
# cluster doctor (server/health.py): verdict + reasons + messages +
# probe bands + recovery timeline + lag rollups, without the rest of
# the status doc — what `fdbcli doctor` and tools/doctor.py poll
HEALTH = b"\xff\xff/status/health"
METRICS_JSON = b"\xff\xff/metrics/json"
# workload attribution (utils/heatmap.py): fleet-merged conflict/read/
# write hot ranges + per-tag rollup, without the rest of the status doc
HOT_RANGES = b"\xff\xff/metrics/hot_ranges"
# device-path execution profile (utils/deviceprofile.py): per-resolver
# dispatch/pad/fallback accounting + the cluster aggregate, without the
# rest of the status doc — what `fdbcli profile` polls
DEVICE = b"\xff\xff/metrics/device"
# metrics history (utils/timeseries.py): bounded per-metric windows
# (counter rates, gauge rollups, latency p99 trajectories) + verdict
# timeline — what `fdbcli history` and the --trend tools poll
HISTORY = b"\xff\xff/metrics/history"
# flight recorder (utils/timeseries.py): dump summary + the newest
# black-box artifact — what tools/flight.py reads from a live cluster
FLIGHT = b"\xff\xff/status/flight"
# continuous consistency scan (server/consistencyscan.py): round,
# progress, bytes/keys scanned, confirmed inconsistencies — what
# `fdbcli scan status` and tools/doctor.py --scan poll
CONSISTENCY_SCAN = b"\xff\xff/status/consistency_scan"
CONNECTION_STRING = b"\xff\xff/connection_string"
CONFLICTING_KEYS = b"\xff\xff/transaction/conflicting_keys/"
EXCLUDED = b"\xff\xff/management/excluded/"
DB_LOCKED = b"\xff\xff/management/db_locked"
# distributed tracing (ref: the \xff\xff/tracing/ module in
# SpecialKeySpace.actor.cpp): ``token`` is TRANSACTION-local — writing
# a nonzero value forces this transaction's trace to be sampled (b"0"
# un-forces); ``sample_rate`` / ``enabled`` are cluster config applied
# at commit like other management writes
TRACING = b"\xff\xff/tracing/"
TRACING_TOKEN = b"\xff\xff/tracing/token"
TRACING_RATE = b"\xff\xff/tracing/sample_rate"
TRACING_ENABLED = b"\xff\xff/tracing/enabled"


def _excluded_rows(tr):
    """Current exclusions overlaid with this txn's pending management
    writes (read-your-writes, like the reference SpecialKeySpace merging
    uncommitted special-space writes into reads)."""
    sids = set(tr._cluster.list_excluded())
    for op, sid in tr._special_writes:
        if op == "exclude":
            sids.add(sid)
        elif op == "include":
            sids.discard(sid)
    return [(EXCLUDED + str(s).encode(), b"") for s in sorted(sids)]


def _conflicting_rows(tr):
    """Boundary encoding: each conflicting range [b, e) contributes
    (prefix+b, "1") and (prefix+e, "0"). Overlapping/adjacent ranges are
    merged first so an interior end key cannot close a region another
    range still covers."""
    ranges = sorted(getattr(tr, "_conflicting_ranges", []) or [])
    merged = []
    for b, e in ranges:
        if merged and b <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([b, e])
    rows = []
    for b, e in merged:
        rows.append((CONFLICTING_KEYS + b, b"1"))
        rows.append((CONFLICTING_KEYS + e, b"0"))
    return rows


def _metrics_json(tr):
    """The metrics section alone (rollups + cluster latency bands) —
    cheaper to poll than the full status document."""
    cluster = tr._cluster
    if hasattr(cluster, "metrics_status"):
        doc = cluster.metrics_status()
    else:  # remote clusters without the endpoint: slice the status doc
        doc = tr.db.status().get("cluster", {}).get("metrics", {})
    return json.dumps(doc, sort_keys=True).encode()


def _hot_ranges_json(tr):
    """The workload-attribution document alone (hot ranges + tags) —
    what `fdbcli top` and tools/heatmap.py poll."""
    cluster = tr._cluster
    if hasattr(cluster, "hot_ranges_status"):
        doc = cluster.hot_ranges_status()
    else:  # remote clusters without the endpoint: slice the status doc
        w = tr.db.status().get("cluster", {}).get("workload", {})
        doc = {"sampling": None,
               "hot_ranges": w.get("hot_ranges", {}),
               "totals": w.get("hot_range_totals", {}),
               "tags": w.get("tags", {})}
    return json.dumps(doc, sort_keys=True).encode()


def _device_json(tr):
    """The device-path execution profile alone (dispatch accounting,
    pad/bucket occupancy, fallback causes, lane walls) — what
    `fdbcli profile` polls."""
    cluster = tr._cluster
    if hasattr(cluster, "device_profile_status"):
        doc = cluster.device_profile_status()
    else:  # remote clusters without the endpoint: slice the status doc
        doc = tr.db.status().get("cluster", {}).get("device", {})
    return json.dumps(doc, sort_keys=True).encode()


def _health_json(tr):
    """The cluster.health document alone (doctor verdict, probe bands,
    recovery timeline, lag rollups) — what `fdbcli doctor` and
    tools/doctor.py poll."""
    cluster = tr._cluster
    if hasattr(cluster, "health_status"):
        doc = cluster.health_status()
    else:  # remote clusters without the endpoint: slice the status doc
        doc = tr.db.status().get("cluster", {}).get("health", {})
    return json.dumps(doc, sort_keys=True).encode()


def _history_json(tr):
    """The metrics-history document alone (per-metric windows, heat
    trajectory, verdict timeline, trend alerts) — what `fdbcli history`
    and the --trend modes of tools/doctor.py and tools/heatmap.py
    poll."""
    cluster = tr._cluster
    if hasattr(cluster, "history_status"):
        doc = cluster.history_status()
    else:  # remote clusters without the endpoint: slice the status doc
        doc = tr.db.status().get("cluster", {}).get("history", {})
    return json.dumps(doc, sort_keys=True).encode()


def _flight_json(tr):
    """The flight-recorder document alone (dump summary + the newest
    black-box artifact) — what tools/flight.py reads post-mortem."""
    cluster = tr._cluster
    if hasattr(cluster, "flight_status"):
        doc = cluster.flight_status()
    else:  # remote clusters without the endpoint: flight summary rides
        # inside the history slice of the status doc; no artifact
        hist = tr.db.status().get("cluster", {}).get("history", {})
        doc = {**hist.get("flight", {}), "artifact": None}
    return json.dumps(doc, sort_keys=True, default=repr).encode()


def _scan_json(tr):
    """The consistency-scan document alone (round, progress, verdict
    counters) — what `fdbcli scan status` and tools/doctor.py --scan
    poll."""
    cluster = tr._cluster
    if hasattr(cluster, "consistency_scan_status"):
        doc = cluster.consistency_scan_status()
    else:  # remote clusters without the endpoint: slice the status doc
        doc = tr.db.status().get("cluster", {}).get("consistency_scan", {})
    return json.dumps(doc, sort_keys=True).encode()


def _tracing_rows(tr):
    """The tracing module's materialized rows (cluster config + this
    transaction's token), RYW-overlaid with pending tracing writes."""
    from foundationdb_tpu.utils import span as span_mod

    cfg = _tracing_config(tr)
    rate, enabled = cfg["sample_rate"], cfg["enabled"]
    for op, val in tr._special_writes:
        if op == "tracing_rate":
            rate, enabled = val, val > 0
        elif op == "tracing_enabled":
            enabled = val
            rate = _DEFAULT_ENABLED_RATE if val and rate <= 0 else (
                rate if val else 0.0
            )
    sp = tr._span
    if tr._trace_forced or (
        sp is not None and sp is not span_mod.NULL and sp.sampled
    ):
        token = (b"%016x" % sp.context()[0]) if sp is not None \
            and sp is not span_mod.NULL else b"1"
    else:
        token = b"0"
    return [
        (TRACING_ENABLED, b"1" if enabled else b"0"),
        (TRACING_RATE, repr(rate).encode()),
        (TRACING_TOKEN, token),
    ]


_DEFAULT_ENABLED_RATE = 0.01  # `tracing on` without an explicit rate


def _tracing_config(tr):
    cluster = tr._cluster
    if hasattr(cluster, "tracing_config"):
        return cluster.tracing_config()
    k = tr._knobs
    return {"enabled": k.tracing_sample_rate > 0,
            "sample_rate": k.tracing_sample_rate}


def get(tr, key):
    if key == STATUS_JSON:
        return json.dumps(tr.db.status(), sort_keys=True).encode()
    if key == HEALTH:
        return _health_json(tr)
    if key == METRICS_JSON:
        return _metrics_json(tr)
    if key == HOT_RANGES:
        return _hot_ranges_json(tr)
    if key == DEVICE:
        return _device_json(tr)
    if key == HISTORY:
        return _history_json(tr)
    if key == FLIGHT:
        return _flight_json(tr)
    if key == CONSISTENCY_SCAN:
        return _scan_json(tr)
    if key == CONNECTION_STRING:
        return tr._cluster.connection_string().encode()
    if key == DB_LOCKED:
        uid = tr._cluster.lock_uid()
        for op, val in tr._special_writes:
            if op == "lock":
                uid = val
            elif op == "unlock":
                uid = None
        return uid
    if key.startswith(TRACING):
        for k, v in _tracing_rows(tr):
            if k == key:
                return v
        return None
    if key.startswith(CONFLICTING_KEYS):
        for k, v in _conflicting_rows(tr):
            if k == key:
                return v
        return None
    if key.startswith(EXCLUDED):
        for k, v in _excluded_rows(tr):
            if k == key:
                return v
        return None
    raise err("key_outside_legal_range")


def get_range(tr, begin, end, limit=0, reverse=False):
    rows = []
    if begin <= STATUS_JSON < end:
        rows.append((STATUS_JSON, get(tr, STATUS_JSON)))
    if begin <= HEALTH < end:
        rows.append((HEALTH, get(tr, HEALTH)))
    if begin <= METRICS_JSON < end:
        rows.append((METRICS_JSON, get(tr, METRICS_JSON)))
    if begin <= HOT_RANGES < end:
        rows.append((HOT_RANGES, get(tr, HOT_RANGES)))
    if begin <= DEVICE < end:
        rows.append((DEVICE, get(tr, DEVICE)))
    if begin <= HISTORY < end:
        rows.append((HISTORY, get(tr, HISTORY)))
    if begin <= FLIGHT < end:
        rows.append((FLIGHT, get(tr, FLIGHT)))
    if begin <= CONSISTENCY_SCAN < end:
        rows.append((CONSISTENCY_SCAN, get(tr, CONSISTENCY_SCAN)))
    if begin <= CONNECTION_STRING < end:
        rows.append((CONNECTION_STRING, get(tr, CONNECTION_STRING)))
    rows += [
        (k, v) for k, v in _conflicting_rows(tr) if begin <= k < end
    ]
    rows += [(k, v) for k, v in _excluded_rows(tr) if begin <= k < end]
    rows += [(k, v) for k, v in _tracing_rows(tr) if begin <= k < end]
    if begin <= DB_LOCKED < end:
        # same RYW overlay as the point get; the row exists only while
        # locked (an unlocked database has no db_locked row to list)
        uid = get(tr, DB_LOCKED)
        if uid is not None:
            rows.append((DB_LOCKED, uid))
    rows.sort(reverse=reverse)
    if limit:
        rows = rows[:limit]
    return rows


def write(tr, key, value):
    """Buffer a management write; applied by ``commit_special``."""
    if key.startswith(EXCLUDED):
        sid = _parse_sid(key)
        tr._special_writes.append(("exclude", sid))
        return
    if key == DB_LOCKED:
        tr._special_writes.append(("lock", value or b"lock"))
        return
    if key == TRACING_TOKEN:
        # txn-local, immediate (ref: the reference's tracing token):
        # nonzero forces THIS transaction sampled, b"0" un-forces
        if value and value != b"0":
            tr.options.set_trace()
        else:
            tr._trace_forced = False
        return
    if key == TRACING_RATE:
        try:
            rate = float(value)
        except (TypeError, ValueError):
            raise err("invalid_option_value") from None
        if not 0.0 <= rate <= 1.0:
            raise err("invalid_option_value")
        tr._special_writes.append(("tracing_rate", rate))
        return
    if key == TRACING_ENABLED:
        tr._special_writes.append(
            ("tracing_enabled", value not in (None, b"", b"0"))
        )
        return
    raise err("key_outside_legal_range")


def clear(tr, key):
    if key.startswith(EXCLUDED):
        sid = _parse_sid(key)
        tr._special_writes.append(("include", sid))
        return
    if key == DB_LOCKED:
        tr._special_writes.append(("unlock", None))
        return
    if key == TRACING_TOKEN:
        tr._trace_forced = False  # txn-local, immediate (like write 0)
        return
    if key == TRACING_ENABLED:
        tr._special_writes.append(("tracing_enabled", False))
        return
    raise err("key_outside_legal_range")


def clear_range(tr, begin, end):
    if begin.startswith(EXCLUDED) and end.startswith(EXCLUDED):
        for k, _ in _excluded_rows(tr):
            if begin <= k < end:
                tr._special_writes.append(("include", _parse_sid(k)))
        return
    raise err("key_outside_legal_range")


def _parse_sid(key):
    raw = key[len(EXCLUDED):]
    try:
        return int(raw.decode())
    except (UnicodeDecodeError, ValueError):
        raise err("invalid_option_value") from None


def commit_special(tr):
    """Apply buffered management writes (commit-time semantics, ref:
    SpecialKeySpace::commit). Idempotent operations; failures surface as
    the commit's error.

    A locked database fences management writes too: unlocking (or any
    other management change) requires the LOCK_AWARE option, exactly as
    the reference's unlockDatabase does — otherwise any fenced client
    could clear the lock through the read-only commit path."""
    if tr._special_writes and not tr._lock_aware:
        if tr._cluster.lock_uid() is not None:
            tr._special_writes = []
            raise err("database_locked")
    for op, arg in tr._special_writes:
        if op == "exclude":
            tr._cluster.exclude_storage(arg)
        elif op == "include":
            tr._cluster.include_storage(arg)
        elif op == "lock":
            tr._cluster.lock_database(arg)
        elif op == "unlock":
            tr._cluster.unlock_database()
        elif op == "tracing_rate":
            tr._cluster.set_tracing(sample_rate=arg)
        elif op == "tracing_enabled":
            tr._cluster.set_tracing(enabled=arg)
    tr._special_writes = []
