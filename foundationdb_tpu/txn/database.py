"""Database handle + retry loop.

Ref parity: fdbclient Database/DatabaseContext plus the Python binding's
``@fdb.transactional`` retry protocol (bindings/python/fdb/impl.py):
run the function, commit, catch retryable errors via on_error, loop.
"""

from foundationdb_tpu.core.errors import FDBError
from foundationdb_tpu.txn.transaction import Transaction


def retry_loop(tr, fn):
    """The transactional retry protocol, shared by Database and Tenant.

    Repair-aware (txn/repair.py): after a conflict the engine repaired
    by replaying the op log verbatim, ``tr.repair_ready`` is set and the
    body must NOT re-run — the restored mutations resubmit as-is (the
    previous attempt's result is the result). Every other retry re-runs
    ``fn`` as usual (a repaired-but-value-dependent retry rides the
    seeded read cache inside the transaction transparently)."""
    result = None
    while True:
        try:
            # getattr: wrapper transactions (TenantTransaction) expose
            # the retry surface but not necessarily the repair flag
            if not getattr(tr, "repair_ready", False):
                result = fn(tr)
            tr.commit()
            return result
        except FDBError as e:
            tr.on_error(e)  # re-raises when not retryable


class Database:
    def __init__(self, cluster):
        self._cluster = cluster

    @property
    def _knobs(self):
        # resolved per use so a swapped cluster (simulated recovery) never
        # leaves transactions running with the dead incarnation's knobs
        return self._cluster.knobs

    def create_transaction(self):
        return Transaction(self)

    def run(self, fn):
        """Execute ``fn(tr)`` transactionally with automatic retries."""
        return retry_loop(self.create_transaction(), fn)

    transact = run

    # one-shot conveniences (binding parity: db[key] etc.)
    def get(self, key):
        return self.run(lambda tr: tr.get(key))

    def set(self, key, value):
        self.run(lambda tr: tr.set(key, value))

    def clear(self, key):
        self.run(lambda tr: tr.clear(key))

    def clear_range(self, begin, end):
        self.run(lambda tr: tr.clear_range(begin, end))

    def get_range(self, begin, end, **kw):
        return self.run(lambda tr: tr.get_range(begin, end, **kw))

    def get_range_startswith(self, prefix, **kw):
        return self.run(lambda tr: tr.get_range_startswith(prefix, **kw))

    def get_key(self, selector):
        return self.run(lambda tr: tr.get_key(selector))

    def watch(self, key):
        out = {}

        def _w(tr):
            out["w"] = tr.watch(key)

        self.run(_w)
        return out["w"]

    def add(self, key, param):
        self.run(lambda tr: tr.add(key, param))

    def __getitem__(self, key):
        if isinstance(key, slice):
            return self.get_range(key.start, key.stop)
        return self.get(key)

    def __setitem__(self, key, value):
        self.set(key, value)

    def __delitem__(self, key):
        if isinstance(key, slice):
            self.clear_range(key.start, key.stop)
        else:
            self.clear(key)

    def open_tenant(self, name):
        from foundationdb_tpu.layers.tenant import Tenant

        return Tenant(self, name)

    # ── change feeds (ref: getChangeFeedStream / change feed API) ──
    def register_change_feed(self, feed_id, begin, end):
        """Subscribe ``feed_id`` to every committed mutation touching
        [begin, end). Mutations stream in commit-version order via
        read_change_feed."""
        self._cluster.change_feeds.register(
            bytes(feed_id), bytes(begin), bytes(end)
        )

    def read_change_feed(self, feed_id, begin_version, end_version=None,
                         limit=0):
        """[(version, [Mutation])] with begin_version < v <= end_version.
        Raises transaction_too_old below the popped/trimmed frontier."""
        return self._cluster.change_feeds.read(
            bytes(feed_id), begin_version, end_version, limit
        )

    def pop_change_feed(self, feed_id, version):
        self._cluster.change_feeds.pop(bytes(feed_id), version)

    def deregister_change_feed(self, feed_id):
        self._cluster.change_feeds.deregister(bytes(feed_id))

    def status(self):
        return self._cluster.status()

    @property
    def options(self):
        return _DatabaseOptions()


class _DatabaseOptions:
    def set_transaction_timeout(self, ms):
        pass

    def set_transaction_retry_limit(self, n):
        pass
