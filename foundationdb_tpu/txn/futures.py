"""Async read futures + the client-side read batcher.

Ref parity: fdbclient/NativeAPI.actor.cpp serves every read through
futures — ``Transaction::get`` returns ``Future<Optional<Value>>`` and
the blocking form is ``wait()`` over the same machinery. Here
:class:`FutureValue` / :class:`FutureRange` are the Python analogs the
transaction layer returns from ``get_async`` / ``get_range_async``,
and :class:`ReadBatcher` is the per-connection multiplexer (the read
analog of the in-repo GRV/commit batchers): N outstanding reads ride
ONE ``read_batch`` RPC — one wire frame, one server GIL crossing —
instead of N blocking round trips.

Settlement discipline (FL002): both future classes are registered as
acquisition constructors in ``analysis/rules/fl002_settlement.py`` —
a constructed read future must be settled, waited, cancelled, or
handed off on every path, exactly like a CommitFuture. The batcher's
``close()`` settles everything still queued with a retryable error
(``process_behind``), so teardown can never strand a waiter.

Waiting (FL003): waiters park on the batcher's shared completion
condition (one notify_all per settled batch — the CommitFuture
lesson: per-future Events were measurable at e2e rates), and the
flusher thread waits only on the condition wrapping its own lock.

Determinism (FL001): no wall clock and no entropy here. The optional
batch window sleeps ``time.sleep`` real time in thread mode only;
immediate mode (manual/sim pipelines) flushes synchronously inside
``submit`` so two same-seed sims issue identical RPC sequences.
"""

import threading

from foundationdb_tpu.core.errors import FDBError, err
from foundationdb_tpu.utils import lockdep
from foundationdb_tpu.utils import span as span_mod

_UNSET = object()


class FutureValue:
    """Resolves to one read's value (or raises its FDBError).

    Lifecycle: constructed by an async read, settled by the batcher
    (``set`` / ``set_exception``), consumed by ``wait()``. An optional
    ``finalize`` callback runs exactly once on the CONSUMING thread —
    the transaction layer uses it for per-key bookkeeping (span
    finish, conflict range, repair op-log) that must happen with the
    settled value but on the caller, not the flusher thread.
    ``wait()`` memoizes the finalized value, so repeated waits are
    free and finalize never runs twice.
    """

    __slots__ = ("_raw", "_error", "_final", "_finalize", "_batcher")

    def __init__(self, batcher=None, finalize=None):
        self._raw = _UNSET
        self._error = None
        self._final = _UNSET
        self._finalize = finalize
        self._batcher = batcher

    def done(self):
        return self._raw is not _UNSET or self._error is not None

    def _notify(self):
        b = self._batcher
        if b is not None:
            with b._done_cond:
                b._done_cond.notify_all()

    def set(self, value):
        """Settle with a value (idempotent: first settlement wins)."""
        if self.done():
            return
        self._raw = value
        self._notify()

    def set_exception(self, error):
        if self.done():
            return
        self._error = error
        self._notify()

    def wait(self):
        """Block until settled, run finalize once, return the value
        (or raise the per-key FDBError). The sync read forms are
        exactly ``get_async(...).wait()``."""
        if self._final is not _UNSET:
            return self._final
        if not self.done():
            b = self._batcher
            if b is None:
                raise err("client_invalid_operation")
            cond = b._done_cond
            # bounded waits + the batcher's stranded-batch watchdog:
            # if the in-flight send outlives its deadline (a wedged
            # peer past even the transport's sweep), the WAITER settles
            # the batch retryably instead of parking forever (FL002's
            # settle-and-retry, not teardown-or-hang). The condition is
            # never held while the watchdog runs — no lock-order edge
            # between _done_cond and the batcher's queue lock.
            while not self.done():
                with cond:
                    cond.wait_for(self.done, timeout=0.25)
                if not self.done():
                    b.check_stranded()
        fin, self._finalize = self._finalize, None
        e = self._error
        if e is not None:
            if fin is not None:
                fin(None, e)
            raise e
        val = self._raw
        if fin is not None:
            val = fin(val, None)
        self._final = val
        return val

    def cancel(self, error=None):
        """Settle an unsettled future with a retryable error and run
        any pending finalize for its cleanup side (swallowing the
        error) — the teardown path ``Transaction._reset`` uses so an
        abandoned async read never strands bookkeeping (FL002)."""
        if not self.done():
            self.set_exception(
                error if error is not None else err("transaction_cancelled")
            )
        fin, self._finalize = self._finalize, None
        if fin is not None and self._final is _UNSET:
            try:
                if self._error is not None:
                    fin(None, self._error)
                elif self._raw is not _UNSET:
                    # settled with a value but never consumed: run the
                    # success-path bookkeeping with the real value
                    self._final = fin(self._raw, None)
            except FDBError:
                pass


class FutureRange(FutureValue):
    """A FutureValue resolving to list[(key, value)] — the async
    ``get_range`` result (distinct type for API parity with the
    reference's Future<RangeResult>; behavior is inherited)."""

    __slots__ = ()


class ReadBatcher:
    """Per-connection read multiplexer (ref: NativeAPI coalescing
    outstanding reads toward storage; the read-side analog of
    ``_CoalescingGrvProxy``): async reads enqueue (op, future) pairs
    and a flusher drains up to ``max_keys`` of them into one
    ``send(ops) -> [value-or-FDBError, ...]`` call.

    ``thread=True`` (live deployments): a daemon flusher thread wakes
    on the first submit, optionally lingers ``window_s``, then sends.
    ``thread=False`` (manual/sim pipelines): ``submit`` flushes
    synchronously — deterministic, and still batched when the caller
    queued several ops before the first ``wait()``.

    Partial failure: a per-op FDBError slot settles ONLY that op's
    future; a transport-level failure settles the whole batch with a
    retryable error (the client retry loop owns it from there).
    """

    # extra slack past the read deadline before the waiter-side
    # watchdog declares an in-flight batch stranded: the transport's
    # own deadline sweep should have settled it long before this
    WATCHDOG_GRACE_S = 1.0

    def __init__(self, send, max_keys=128, window_s=0.0, thread=True,
                 deadline_s=None):
        self._send_fn = send
        self.max_keys = max(1, int(max_keys))
        self.window_s = float(window_s)
        self.deadline_s = deadline_s  # None = watchdog disabled
        self._lock = lockdep.lock("ReadBatcher._lock")
        self._wake = lockdep.condition("ReadBatcher._lock", self._lock)
        self._done_cond = lockdep.condition("ReadBatcher._done_cond")  # shared waiter parking
        self._queue = []  # [(op, future, span_ctx)]
        self._inflight = None  # batch currently inside _send_fn
        self._inflight_since = 0.0
        self.stranded_settled = 0  # watchdog interventions (observability)
        self._closed = False
        self.batches_sent = 0
        self.ops_sent = 0
        self._thread = None
        if thread:
            self._thread = threading.Thread(
                target=self._flusher_loop, name="read-batcher", daemon=True
            )
            self._thread.start()

    # ── client surface ──
    def submit(self, op, fut, ctx=None):
        """Enqueue one read op for its constructed future (the caller
        holds the future — FL002 handoff happens at this call)."""
        with self._lock:
            if self._closed:
                closed = True
            else:
                closed = False
                self._queue.append((op, fut, ctx))
                self._wake.notify()
        if closed:
            fut.set_exception(err("process_behind"))
            return
        if self._thread is None:
            self._flush_now()

    def pending(self):
        with self._lock:
            return len(self._queue)

    def _drain(self):
        with self._lock:
            batch, self._queue = (
                self._queue[: self.max_keys],
                self._queue[self.max_keys:],
            )
        return batch

    def _flush_now(self):
        batch = self._drain()
        while batch:
            self._send_batch(batch)
            batch = self._drain()

    # ── flusher ──
    def _flusher_loop(self):
        import time

        while True:
            with self._wake:
                while not self._queue and not self._closed:
                    self._wake.wait()
                if self._closed:
                    return  # close() settles what remains queued
            if self.window_s:
                time.sleep(self.window_s)  # linger: let a window pile in
            self._flush_now()

    def check_stranded(self):
        """Waiter-side watchdog: a batch stuck inside ``_send_fn`` past
        deadline + grace gets its futures settled retryably HERE, on
        the waiting thread — a wedged send can strand the flusher
        thread but never a caller. Settlement runs outside the queue
        lock (the futures notify ``_done_cond``); the real send's
        eventual settle attempts are no-ops (first settlement wins)."""
        import time

        if self.deadline_s is None:
            return
        bound = self.deadline_s + self.WATCHDOG_GRACE_S
        with self._lock:
            batch = self._inflight
            if batch is None \
                    or time.monotonic() - self._inflight_since < bound:
                return
            self._inflight = None  # claimed: exactly one waiter settles
            self.stranded_settled += len(batch)
        for _, fut, _ in batch:
            fut.set_exception(err("process_behind"))

    def _send_batch(self, batch):
        """One multiplexed RPC for ``batch``; every member future
        settles here no matter how the send fails (FL002)."""
        import time

        with self._lock:
            self._inflight = batch
            self._inflight_since = time.monotonic()
        try:
            self._send_batch_inner(batch)
        finally:
            with self._lock:
                if self._inflight is batch:
                    self._inflight = None

    def _send_batch_inner(self, batch):
        # the batch's span context: the FIRST sampled member's — the
        # server parents its storage.read_batch span to that trace
        # (the commit batcher's first_request_context idiom)
        ctx = None
        for _, _, c in batch:
            if c is not None and c[2]:
                ctx = c
                break
        prior = span_mod.set_current(ctx)
        try:
            slots = self._send_fn([op for op, _, _ in batch])
        except FDBError as e:
            for _, fut, _ in batch:
                fut.set_exception(e)
            return
        except Exception:
            # transport-level failure: every op retries via the client
            # loop (the _RemoteStorage path already exhausted reconnect)
            for _, fut, _ in batch:
                fut.set_exception(err("process_behind"))
            return
        finally:
            span_mod.set_current(prior)
        with self._lock:  # stats shared with submit()-side readers
            self.batches_sent += 1
            self.ops_sent += len(batch)
        for (_, fut, _), slot in zip(batch, slots):
            if isinstance(slot, FDBError):
                fut.set_exception(slot)  # per-key: not batch-fatal
            else:
                fut.set(slot)

    def close(self):
        """Settle every queued read with a retryable error and stop
        the flusher — teardown can never strand a waiter (FL002)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pending, self._queue = self._queue, []
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
        for _, fut, _ in pending:
            fut.set_exception(err("process_behind"))
