"""fdbserver-shaped process entry: host a cluster (and a coordinator
replica) behind the RPC transport.

Ref parity: fdbserver/fdbserver.actor.cpp's worker process — started
with a listen address and a data directory, it serves the database to
any client holding the cluster file. Every process also hosts a
coordinator replica (ref: coordinators are fdbserver processes named in
the cluster file); ``--coordinators`` points recovery at a quorum of
peer processes, and ``--coordinator-only`` runs just the replica, so a
deployment looks like the reference's: N coordinator processes + a
transaction-system process, with recovery locking the generation
through a real network majority.

Usage::

    # three coordinators
    python -m foundationdb_tpu.tools.fdbserver --listen 127.0.0.1:4510 \
        --coordinator-only --dir /var/co1   (and 4511, 4512...)
    # the database server, recovering through that quorum
    python -m foundationdb_tpu.tools.fdbserver \
        --listen 127.0.0.1:4500 --dir /var/db --cluster-file fdb.cluster \
        --coordinators 127.0.0.1:4510,127.0.0.1:4511,127.0.0.1:4512

The cluster file is (re)written with this server's address on startup,
so `foundationdb_tpu.open(cluster_file=...)` finds it.
"""

import argparse
import os
import signal
import sys
import threading

from foundationdb_tpu.core.options import Knobs
from foundationdb_tpu.rpc.coordination import CoordinatorService, remote_quorum
from foundationdb_tpu.rpc.service import (
    ClusterService,
    write_cluster_file,
)
from foundationdb_tpu.rpc.transport import RpcServer
from foundationdb_tpu.utils.trace import TraceEvent


def build_cluster(args, coordination=None):
    from foundationdb_tpu.server.cluster import Cluster

    kw = {}
    if args.dir:
        os.makedirs(args.dir, exist_ok=True)
        kw["wal_path"] = os.path.join(args.dir, "tlog.wal")
        if coordination is None:
            kw["coordination_dir"] = os.path.join(args.dir, "coordination")
    engine = getattr(args, "storage_engine", None)
    if engine:
        from foundationdb_tpu.server.kvstore import open_engine

        if not args.dir and engine != "memory":
            raise SystemExit(f"--storage-engine {engine} requires --dir")
        base = os.path.join(args.dir, "store") if args.dir else None
        kw["storage_engines"] = [
            open_engine(engine, None if base is None else f"{base}.{i}")
            for i in range(args.storage)
        ]
    return Cluster(
        n_storage=args.storage,
        n_resolvers=args.resolvers,
        n_commit_proxies=args.commit_proxies,
        n_tlogs=args.tlogs,
        replication=args.replication,
        fsync=args.fsync,
        commit_pipeline=args.commit_pipeline,
        resolver_backend=args.resolver_backend,
        coordination=coordination,
        **kw,
    )


def main(argv=None):
    p = argparse.ArgumentParser(prog="fdbserver")
    p.add_argument("--listen", default="127.0.0.1:0",
                   help="host:port to listen on (port 0 = ephemeral)")
    p.add_argument("--cluster-file", default=None,
                   help="cluster file to write this server's address into")
    p.add_argument("--dir", default=None, help="data directory (WAL, paxos)")
    p.add_argument("--coordinators", default=None,
                   help="comma-separated coordinator addresses; recovery "
                        "locks its generation through this quorum")
    p.add_argument("--coordinator-only", action="store_true",
                   help="host only the coordinator replica (no database)")
    p.add_argument("--join", default=None, metavar="LEAD",
                   help="run as a storage-worker process: pull the "
                        "mutation stream from the lead server at this "
                        "address and serve versioned reads")
    p.add_argument("--tag", type=int, default=None,
                   help="with --join: subscribe to ONE storage tag's "
                        "log stream and serve only its owned ranges "
                        "(tag-partitioned log; default: full stream)")
    p.add_argument("--storage", type=int, default=1)
    p.add_argument("--storage-engine", default=None,
                   choices=["memory", "sqlite", "versioned", "redwood"],
                   help="persistent engine beneath each storage server "
                        "(ref: `configure ssd|memory`; redwood = the "
                        "disk-resident versioned engine; disk kinds "
                        "need --dir)")
    p.add_argument("--resolvers", type=int, default=1)
    p.add_argument("--commit-proxies", type=int, default=1,
                   help="commit-proxy fleet size (sequencer-chained "
                        "version grants; ref: the proxy count in "
                        "`configure`)")
    p.add_argument("--tlogs", type=int, default=1)
    p.add_argument("--replication", type=int, default=None)
    p.add_argument("--fsync", action="store_true")
    p.add_argument("--commit-pipeline", default="thread",
                   choices=["sync", "manual", "thread"],
                   help="thread = cross-client commit/GRV batching (default)")
    p.add_argument("--resolver-backend", default="cpu",
                   choices=["tpu", "cpu", "native"])
    p.add_argument("--monitor-interval", type=float, default=0.5,
                   help="failure-detection round interval, seconds")
    p.add_argument("--auth-secret", default=None,
                   help="shared secret for the transport handshake; every "
                        "process and client of the cluster must use the "
                        "same one (defaults to $FDB_TPU_AUTH_SECRET)")
    p.add_argument("--switch-interval", type=float, default=None,
                   metavar="SECONDS",
                   help="CPython thread switch interval for this server "
                        "process (default: the server_switch_interval_s "
                        "knob; 0 keeps the interpreter default)")
    args = p.parse_args(argv)
    secret = args.auth_secret or os.environ.get("FDB_TPU_AUTH_SECRET")

    # Read-RPC latency under commit load: CPython schedules a waiting
    # thread only every sys.getswitchinterval() (default 5ms), so a
    # read RPC landing while a commit batch holds this process's GIL
    # waits out the slice. Measured on the multiproc bench harness:
    # 223us/read idle, 5.6ms under write load at the default interval,
    # 4.2ms at 0.5ms — the residue is GIL convoy on both ends of the
    # synchronous read (see bench.py e2e_multiproc_bottleneck). Commit
    # throughput is unaffected (its hot sections are numpy/C calls).
    # Tunable as the server_switch_interval_s knob / --switch-interval.
    switch_s = args.switch_interval
    if switch_s is None:
        switch_s = Knobs().server_switch_interval_s
    if switch_s > 0:
        sys.setswitchinterval(switch_s)

    host, _, port = args.listen.rpartition(":")
    if secret is None and host not in ("", "127.0.0.1", "localhost",
                                       "::1", "[::1]"):
        print(
            "warning: --listen on a non-loopback interface without "
            "--auth-secret exposes unauthenticated read/write/management "
            "access to anyone who can reach the port",
            file=sys.stderr, flush=True,
        )

    if args.join:
        # storage-worker process: no coordinator, no local cluster —
        # a local store fed by pulling the lead's log (ref: a storage
        # process's update loop pulling its tag from the TLogs)
        from foundationdb_tpu.rpc.storageworker import StorageWorker

        worker = StorageWorker(args.join, secret=secret,
                               tag=args.tag).start()
        worker.wait_caught_up()
        server = worker.serve(host or "127.0.0.1", int(port))
        stop = threading.Event()
        signal.signal(signal.SIGTERM, lambda s, f: stop.set())
        signal.signal(signal.SIGINT, lambda s, f: stop.set())
        print(f"FDBD listening on {server.address} (storage-worker)",
              flush=True)
        TraceEvent("FdbServerUp").detail(
            address=server.address, role="storage-worker",
            pid=os.getpid()).log()
        stop.wait()
        server.close()
        worker.close()
        return 0

    # coordinator endpoints come up FIRST: peer recoveries must be able
    # to reach this replica before (and regardless of) any local cluster
    coord_path = None
    if args.dir:
        os.makedirs(args.dir, exist_ok=True)
        coord_path = os.path.join(args.dir, "coordinator.json")
    coord = CoordinatorService(coord_path)
    server = RpcServer(host or "127.0.0.1", int(port), coord.handlers(),
                       secret=secret)

    cluster = None
    if args.coordinator_only and args.cluster_file:
        print(
            "warning: --cluster-file is ignored with --coordinator-only "
            "(clients connect to a database server, not a coordinator)",
            file=sys.stderr, flush=True,
        )
    if not args.coordinator_only:
        coordination = None
        if args.coordinators:
            coordination = remote_quorum(
                [a.strip() for a in args.coordinators.split(",")],
                secret=secret,
            )
        cluster = build_cluster(args, coordination)
        service = ClusterService(cluster)
        server.add_handlers(service.handlers(), long_methods={"watch_wait"})
        # log-feed endpoints so --join storage-worker processes can pull
        from foundationdb_tpu.rpc.storageworker import LogFeed

        server.add_handlers(LogFeed(cluster).handlers(),
                            long_methods={"tlog_peek"})
        if args.cluster_file:
            write_cluster_file(args.cluster_file, [server.address])

    stop = threading.Event()

    def _shutdown(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)

    role = "coordinator" if args.coordinator_only else "fdbserver"
    print(f"FDBD listening on {server.address} ({role})", flush=True)
    TraceEvent("FdbServerUp").detail(
        address=server.address, role=role, pid=os.getpid()).log()
    # the operator loop the simulation normally pumps: failure detection
    # + recruitment (ref: ClusterController's failureDetectionServer)
    while not stop.wait(args.monitor_interval):
        if cluster is None:
            continue
        try:
            cluster.detect_and_recruit()
        except Exception as e:  # keep serving; log the monitor hiccup
            TraceEvent("FailureMonitorError", severity=30).detail(
                error=repr(e)).log()

    server.close()
    if cluster is not None:
        cluster.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
