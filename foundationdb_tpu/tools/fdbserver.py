"""fdbserver-shaped process entry: host a cluster behind the RPC
transport.

Ref parity: fdbserver/fdbserver.actor.cpp's worker process — started
with a listen address and a data directory, it serves the database to
any client holding the cluster file. Role topology (storage count,
resolvers, tlog replicas, replication factor) is configured by flags the
way the reference's is configured through the cluster.

Usage::

    python -m foundationdb_tpu.tools.fdbserver \
        --listen 127.0.0.1:4500 --dir /var/db --cluster-file fdb.cluster

The cluster file is (re)written with this server's address on startup,
so `foundationdb_tpu.open(cluster_file=...)` finds it.
"""

import argparse
import os
import signal
import sys
import threading

from foundationdb_tpu.rpc.service import serve_cluster, write_cluster_file
from foundationdb_tpu.server.cluster import Cluster
from foundationdb_tpu.utils.trace import TraceEvent


def build_cluster(args):
    kw = {}
    if args.dir:
        os.makedirs(args.dir, exist_ok=True)
        kw["wal_path"] = os.path.join(args.dir, "tlog.wal")
        kw["coordination_dir"] = os.path.join(args.dir, "coordination")
    return Cluster(
        n_storage=args.storage,
        n_resolvers=args.resolvers,
        n_tlogs=args.tlogs,
        replication=args.replication,
        fsync=args.fsync,
        commit_pipeline=args.commit_pipeline,
        resolver_backend=args.resolver_backend,
        **kw,
    )


def main(argv=None):
    p = argparse.ArgumentParser(prog="fdbserver")
    p.add_argument("--listen", default="127.0.0.1:0",
                   help="host:port to listen on (port 0 = ephemeral)")
    p.add_argument("--cluster-file", default=None,
                   help="cluster file to write this server's address into")
    p.add_argument("--dir", default=None, help="data directory (WAL, paxos)")
    p.add_argument("--storage", type=int, default=1)
    p.add_argument("--resolvers", type=int, default=1)
    p.add_argument("--tlogs", type=int, default=1)
    p.add_argument("--replication", type=int, default=None)
    p.add_argument("--fsync", action="store_true")
    p.add_argument("--commit-pipeline", default="thread",
                   choices=["sync", "manual", "thread"],
                   help="thread = cross-client commit/GRV batching (default)")
    p.add_argument("--resolver-backend", default="cpu",
                   choices=["tpu", "cpu", "native"])
    p.add_argument("--monitor-interval", type=float, default=0.5,
                   help="failure-detection round interval, seconds")
    args = p.parse_args(argv)

    host, _, port = args.listen.rpartition(":")
    cluster = build_cluster(args)
    server = serve_cluster(cluster, host or "127.0.0.1", int(port))
    if args.cluster_file:
        write_cluster_file(args.cluster_file, [server.address])

    stop = threading.Event()

    def _shutdown(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)

    # the operator loop the simulation normally pumps: failure detection
    # + recruitment (ref: ClusterController's failureDetectionServer)
    print(f"FDBD listening on {server.address}", flush=True)
    TraceEvent("FdbServerUp").detail(
        address=server.address, pid=os.getpid()).log()
    while not stop.wait(args.monitor_interval):
        try:
            cluster.detect_and_recruit()
        except Exception as e:  # keep serving; log the monitor hiccup
            TraceEvent("FailureMonitorError", severity=30).detail(
                error=repr(e)).log()

    server.close()
    cluster.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
