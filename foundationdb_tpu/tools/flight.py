r"""Flight-recorder post-mortem: read the black box back.

Ref parity: the operator workflow around FoundationDB incident
forensics — trace logs plus the status history around the event. Here
the input is a flight artifact (utils/timeseries.py FlightRecorder):
the bounded dump a health-verdict transition, txn-system recovery, or
probe-SLO breach produced, either as the JSON file written under
``knobs.flight_dir`` or live off a cluster's ``flight`` RPC /
``\xff\xff/status/flight`` special key::

    python -m foundationdb_tpu.tools.flight --json flight-0000.json
    python -m foundationdb_tpu.tools.flight --connect host:4500

The report answers the first three incident questions: what tripped
the recorder (triggers + verdict timeline), what the workload was
doing (committed/conflict/read rate trends across the retained
windows), and where the commit pipeline's time went (hottest-stage
trajectory). Pure helpers (``rate_trends`` / ``hottest_stages`` /
``verdict_timeline``) take the artifact dict directly so chaos tests
can assert on them without a subprocess.
"""

import json
import sys

STAGES = ("pack", "dispatch", "resolve", "apply")


def rate_trends(artifact, names=("txn_committed", "txn_conflicted",
                                 "reads", "admit_denied")):
    """{counter: [rate, ...]} across the artifact's retained windows —
    the workload's shape leading into the incident."""
    counters = (artifact.get("windows") or {}).get("counters") or {}
    return {
        name: [r["rate"] for r in counters.get(name) or []]
        for name in names
    }


def hottest_stages(artifact):
    """Per retained window, the commit-pipeline stage that burned the
    most busy-time: ``[{t, stage, rate_s_per_s}, ...]``. The stage_*_s
    counters are busy-SECONDS totals, so each window's rate is
    seconds-per-second — directly comparable across stages."""
    counters = (artifact.get("windows") or {}).get("counters") or {}
    per_stage = {s: counters.get(f"stage_{s}_s") or [] for s in STAGES}
    depth = max((len(rows) for rows in per_stage.values()), default=0)
    out = []
    for i in range(depth):
        best, best_rate, t = None, -1.0, None
        for stage, rows in per_stage.items():
            if i < len(rows):
                t = rows[i]["t"]
                if rows[i]["rate"] > best_rate:
                    best, best_rate = stage, rows[i]["rate"]
        out.append({"t": t, "stage": best,
                    "rate_s_per_s": round(max(best_rate, 0.0), 6)})
    return out


def verdict_timeline(artifact):
    """[(t, verdict, reasons)] — the health trajectory the recorder
    retained around the trigger."""
    return [
        (v["t"], v["verdict"], list(v.get("reasons") or ()))
        for v in artifact.get("verdict_timeline") or []
    ]


def report(artifact, out=None):
    """Human-readable post-mortem for one artifact."""
    out = out if out is not None else sys.stdout

    def p(line=""):
        print(line, file=out)

    p(f"Flight artifact seq={artifact.get('seq')} "
      f"t={artifact.get('t')} generation={artifact.get('generation')}")
    p(f"  verdict: {artifact.get('verdict')} "
      f"reasons={artifact.get('reasons') or []}")
    p(f"  triggers: {artifact.get('triggers') or []}")
    if artifact.get("path"):
        p(f"  path: {artifact['path']}")
    p("Rate trends (per window, /s):")
    for name, rates in sorted(rate_trends(artifact).items()):
        if rates:
            p(f"  {name:<16}- " + " ".join(str(r) for r in rates))
    hs = hottest_stages(artifact)
    if hs:
        p("Hottest stage trajectory:")
        for h in hs:
            p(f"  t={h['t']}: {h['stage']} "
              f"({h['rate_s_per_s']} busy-s/s)")
    p("Verdict timeline:")
    for t, verdict, reasons in verdict_timeline(artifact):
        suffix = f" {reasons}" if reasons else ""
        p(f"  t={t}: {verdict}{suffix}")
    rec = artifact.get("recovery") or {}
    if rec.get("records"):
        p("Recovery timeline:")
        for r in rec["records"]:
            p(f"  gen {r.get('generation')}: {r.get('trigger')} "
              f"({r.get('total_ms')} ms)")
    sites = artifact.get("buggify_sites") or []
    if sites:
        p(f"Activated buggify sites: {', '.join(sites)}")
    tail = artifact.get("trace_tail") or []
    p(f"Trace tail: {len(tail)} event(s) retained")


def _fetch_artifact(ns):
    if ns.json == "-":
        doc = json.load(sys.stdin)
    elif ns.json:
        with open(ns.json) as f:
            doc = json.load(f)
    else:
        from foundationdb_tpu.rpc.service import RemoteCluster

        rc = RemoteCluster([ns.connect])
        try:
            doc = rc.flight_status()
        finally:
            rc.close()
    # the flight RPC / special key wraps the newest artifact in the
    # dump summary; a flight_dir file IS the artifact
    if isinstance(doc, dict) and "artifact" in doc \
            and "flight_schema" not in doc:
        return doc["artifact"], doc
    return doc, None


def main(argv=None, out=None):
    import argparse

    out = out if out is not None else sys.stdout
    ap = argparse.ArgumentParser(
        prog="python -m foundationdb_tpu.tools.flight",
        description="post-mortem report over a flight-recorder artifact")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--json", metavar="PATH",
                     help="a flight-NNNN.json artifact (- = stdin)")
    src.add_argument("--connect", metavar="HOST:PORT",
                     help="read the newest artifact off a live cluster")
    ap.add_argument("--raw", action="store_true",
                    help="dump the artifact JSON instead of the report")
    ns = ap.parse_args(argv)
    artifact, summary = _fetch_artifact(ns)
    if artifact is None:
        dumps = (summary or {}).get("dumps", 0)
        print(f"No flight artifact recorded ({dumps} dumps).", file=out)
        return 1
    if ns.raw:
        print(json.dumps(artifact, indent=2, sort_keys=True,
                         default=repr), file=out)
        return 0
    report(artifact, out=out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
