"""Critical-path analysis over emitted Span events.

The offline half of the distributed-tracing subsystem (utils/span.py):
finished spans land as ``type="Span"`` JSON lines in the ordinary trace
files (rolled like everything else), and this tool reconstructs the
span trees and answers "where did the slow commits spend their time" —
per-hop count/p50/p99/total, the hottest parent→child EDGE by total
wall time, and the hottest pipeline STAGE (the ``stage.*`` spans mirror
server/batcher.py's StageStats split, so the attribution here is
cross-checkable against ``stage_summary()``'s hottest stage).

Usage::

    python -m foundationdb_tpu.tools.tracing trace.json

Rolled siblings are stitched automatically: the rolling file sink
(utils/trace.py) rotates ``path`` → ``path.1`` → … → ``path.N`` with
``path.N`` the oldest, so giving the live path reads the WHOLE history
oldest-first instead of silently analyzing only the newest fragment.

Programmatically: ``report(spans)`` over ``load_spans(...)`` /
in-memory ``events("Span")`` dicts from a TraceLog ring buffer.
"""

import json
import os
import sys

STAGE_PREFIX = "stage."


def rolled_files(path):
    """The rolled family of a live trace path, oldest first:
    ``path.N … path.1 path`` (the rolling sink shifts contiguously, so
    the scan stops at the first missing index). A path with no rolls —
    or an explicitly-given ``path.K`` sibling — returns just itself."""
    rolls = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        rolls.append(f"{path}.{i}")
        i += 1
    out = list(reversed(rolls))
    if os.path.exists(path) or not out:
        out.append(path)
    return out


def stitch(paths):
    """Expand each given path through its rolled family, deduplicated
    and ordered oldest-first per family."""
    out = []
    for p in paths:
        for q in rolled_files(p):
            if q not in out:
                out.append(q)
    return out


def load_spans(paths):
    """Span events from trace files (JSON lines; non-Span and
    unparseable lines are skipped — trace files interleave everything)."""
    spans = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("type") == "Span":
                    spans.append(ev)
    return spans


def build_trees(spans):
    """{trace_id: {"spans": {sid: span}, "children": {sid: [sid]},
    "roots": [sid]}} — the per-trace tree index. A span whose parent is
    missing from the capture (sampling started mid-trace, rolled-away
    file) is treated as a root of its own subtree."""
    traces = {}
    for ev in spans:
        t = traces.setdefault(
            ev["trace"], {"spans": {}, "children": {}, "roots": []}
        )
        t["spans"][ev["sid"]] = ev
    for t in traces.values():
        for sid, ev in t["spans"].items():
            parent = ev.get("parent", "0" * 16)
            if parent in t["spans"]:
                t["children"].setdefault(parent, []).append(sid)
            else:
                t["roots"].append(sid)
    return traces


def _percentile(ordered, q):
    if not ordered:
        return 0.0
    i = min(len(ordered) - 1, int(q * (len(ordered) - 1) + 0.5))
    return ordered[i]


def hop_stats(spans):
    """Per span-name latency bands: {name: {count, p50_ms, p99_ms,
    max_ms, total_ms, self_ms}} — the "which hop is slow" table.
    ``self_ms`` is EXCLUSIVE time (duration minus captured direct
    children), the honest per-hop attribution when hops nest."""
    child_sum = {}
    for ev in spans:
        key = (ev["trace"], ev.get("parent"))
        child_sum[key] = child_sum.get(key, 0.0) + ev.get("dur_ms", 0.0)
    by_name = {}
    self_by_name = {}
    for ev in spans:
        name = ev["span"]
        dur = ev.get("dur_ms", 0.0)
        by_name.setdefault(name, []).append(dur)
        own = max(0.0, dur - child_sum.get((ev["trace"], ev["sid"]), 0.0))
        self_by_name[name] = self_by_name.get(name, 0.0) + own
    out = {}
    for name, durs in sorted(by_name.items()):
        durs.sort()
        out[name] = {
            "count": len(durs),
            "p50_ms": round(_percentile(durs, 0.50), 3),
            "p99_ms": round(_percentile(durs, 0.99), 3),
            "max_ms": round(durs[-1], 3),
            "total_ms": round(sum(durs), 3),
            "self_ms": round(self_by_name[name], 3),
        }
    return out


def hottest_edge(spans):
    """The parent→child edge with the most TOTAL child wall time — the
    commit pipeline's critical path as the traces measured it. Returns
    (edge_name, total_ms) or (None, 0.0)."""
    by_sid = {(ev["trace"], ev["sid"]): ev for ev in spans}
    totals = {}
    for ev in spans:
        parent = by_sid.get((ev["trace"], ev.get("parent")))
        if parent is None:
            # a root's duration is the whole trace, not an attribution
            # — only real parent→child edges say WHERE the time went
            continue
        edge = f"{parent['span']}->{ev['span']}"
        totals[edge] = totals.get(edge, 0.0) + ev.get("dur_ms", 0.0)
    if not totals:
        return None, 0.0
    # deterministic tie-break: by total desc, then name
    edge = min(totals, key=lambda e: (-totals[e], e))
    return edge, round(totals[edge], 3)


def hottest_stage(spans):
    """Among the ``stage.*`` spans (the batcher's pack/dispatch/
    resolve/apply split), the stage with the most total wall time —
    comparable 1:1 with stage_summary()'s hottest-stage attribution."""
    totals = {}
    for ev in spans:
        name = ev["span"]
        if name.startswith(STAGE_PREFIX):
            stage = name[len(STAGE_PREFIX):]
            totals[stage] = totals.get(stage, 0.0) + ev.get("dur_ms", 0.0)
    if not totals:
        return None
    return min(totals, key=lambda s: (-totals[s], s))


def report(spans):
    """The full analysis document: tree counts, per-hop bands, hottest
    edge/stage, and the single slowest trace's hop breakdown."""
    trees = build_trees(spans)
    edge, edge_ms = hottest_edge(spans)
    slowest = None
    for trace_id, t in trees.items():
        for rid in t["roots"]:
            root = t["spans"][rid]
            if slowest is None or root.get("dur_ms", 0.0) > \
                    slowest[1].get("dur_ms", 0.0):
                slowest = (trace_id, root, t)
    slowest_doc = None
    if slowest is not None:
        trace_id, root, t = slowest
        slowest_doc = {
            "trace": trace_id,
            "root": root["span"],
            "dur_ms": root.get("dur_ms", 0.0),
            "hops": {
                ev["span"]: ev.get("dur_ms", 0.0)
                for ev in t["spans"].values()
            },
        }
    return {
        "spans": len(spans),
        "traces": len(trees),
        "hops": hop_stats(spans),
        "hottest_edge": edge,
        "hottest_edge_total_ms": edge_ms,
        "hottest_stage": hottest_stage(spans),
        "slowest_trace": slowest_doc,
    }


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m foundationdb_tpu.tools.tracing",
        description="reconstruct span trees from trace files and "
                    "report per-hop latency + critical-path attribution",
    )
    ap.add_argument("files", nargs="+",
                    help="trace files (JSON lines); rolled .1….N "
                         "siblings are stitched in automatically")
    ns = ap.parse_args(argv)
    spans = load_spans(stitch(ns.files))
    print(json.dumps(report(spans), indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
