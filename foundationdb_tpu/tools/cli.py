r"""fdbcli — the interactive/scripted cluster shell.

Ref parity: fdbcli/fdbcli.actor.cpp. Same command set where it makes
sense in-process: get/set/clear/clearrange/getrange/getrangekeys,
begin/commit/reset (explicit transaction mode), writemode, status
[json], getversion, advanceversion, option, tenant
create/list/delete/get, kill/suspend analogs are out of scope (no
process model yet). Key literals use fdbcli's escaping: printable
bytes plus \xNN, \\, quoted strings.

Usage::

    from foundationdb_tpu.tools.cli import Cli
    Cli(db).run_command('set hello world')
    Cli(db).repl()              # interactive

or ``python -m foundationdb_tpu.tools.cli --exec "status json"``.
"""

import json
import shlex
import sys

from foundationdb_tpu.core.errors import FDBError


def parse_key(token):
    r"""fdbcli key literal → bytes (handles \xNN and \\ escapes)."""
    out = bytearray()
    i = 0
    while i < len(token):
        c = token[i]
        if c == "\\" and i + 1 < len(token):
            n = token[i + 1]
            if n == "x" and i + 3 < len(token):
                out.append(int(token[i + 2 : i + 4], 16))
                i += 4
                continue
            if n == "\\":
                out.append(0x5C)
                i += 2
                continue
        out.append(ord(c))
        i += 1
    return bytes(out)


def format_key(b):
    """bytes → fdbcli display literal."""
    out = []
    for byte in b:
        if 32 <= byte < 127 and byte != 0x5C:
            out.append(chr(byte))
        elif byte == 0x5C:
            out.append("\\\\")
        else:
            out.append(f"\\x{byte:02x}")
    return "".join(out)


class Cli:
    def __init__(self, db, out=None, open_fn=None):
        self.db = db
        self.out = out if out is not None else sys.stdout
        self.tr = None  # explicit transaction when `begin` is active
        self.write_mode = False
        # metacluster commands open OTHER clusters by cluster file;
        # tests inject an opener that returns in-process databases
        self._open_fn = open_fn
        self._metacluster = None

    def _p(self, *lines):
        for ln in lines:
            print(ln, file=self.out)

    def _run(self, fn):
        """Run against the explicit txn if one is open, else one-shot."""
        if self.tr is not None:
            return fn(self.tr)
        return self.db.run(fn)

    def repl(self, in_=None):
        in_ = in_ if in_ is not None else sys.stdin
        self._p("Welcome to the foundationdb_tpu CLI. Type `help` for help.")
        while True:
            print("fdb> ", end="", flush=True, file=self.out)
            line = in_.readline()
            if not line:
                break
            if not self.run_command(line.strip()):
                break

    def run_command(self, line):
        """Execute one command line. Returns False on exit/quit."""
        if not line or line.startswith("#"):
            return True
        try:
            parts = shlex.split(line)
        except ValueError as e:
            self._p(f"ERROR: {e}")
            return True
        cmd, args = parts[0].lower(), parts[1:]
        if cmd in ("exit", "quit"):
            return False
        handler = getattr(self, f"_cmd_{cmd}", None)
        if handler is None:
            self._p(f"ERROR: Unknown command `{cmd}'. Try `help'.")
            return True
        try:
            handler(args)
        except FDBError as e:
            self._p(f"ERROR: {e} ({e.code})")
        except (ValueError, IndexError) as e:
            self._p(f"ERROR: {e}")
        except OSError as e:
            # bad cluster file / unreachable peer (metacluster register
            # etc.) must not kill the shell — ConnectionLost and
            # FileNotFoundError are both OSErrors
            self._p(f"ERROR: {e}")
        return True

    # ── commands (ref: fdbcli command table) ──
    def _cmd_help(self, args):
        self._p(
            "Commands:",
            "  get KEY                         read a key",
            "  set KEY VALUE                   write a key (writemode on)",
            "  clear KEY                       clear a key (writemode on)",
            "  clearrange BEGIN END            clear a range (writemode on)",
            "  getrange BEGIN [END] [LIMIT]    read a key range",
            "  getrangekeys BEGIN [END] [LIMIT] read keys only",
            "  writemode on|off                allow mutations",
            "  begin / commit / reset          explicit transaction",
            "  getversion                      current read version",
            "  status [json]                   cluster status",
            "  tenant create|delete|list|get   manage tenants",
            "  tenant mode [MODE]              optional|required|disabled",
            "  tenant quota NAME [TPS|clear]   per-tenant rate limit",
            "  throttle list|on tag T TPS|off tag T   per-tag throttling",
            "  top [conflict|read|write] [K]   hottest key ranges + tags",
            "  profile [json]                  device-path dispatch profile",
            "  doctor [json]                   health verdict + SLO alerts",
            "  scan status|on|off              continuous consistency scan",
            "  history [METRIC|json]           metrics history windows",
            "  metacluster create|status|register|attach|remove|tenant",
            "  tracing status|on|off|sample RATE   distributed tracing",
            "  configure commit_proxies=N resolvers=N   live resize",
            "  configure regions=JSON|off      multi-region replication",
            "  exclude [ID]                    drain a storage (list with no arg)",
            "  include ID                      cancel an exclusion",
            "  option ...                      accepted, no-op",
            "  exit / quit",
        )

    def _need_write(self):
        if not self.write_mode:
            raise ValueError(
                "writemode must be enabled to set or clear keys"
            )

    def _cmd_writemode(self, args):
        self.write_mode = args and args[0] == "on"

    def _cmd_get(self, args):
        key = parse_key(args[0])
        val = self._run(lambda tr: tr.get(key))
        if val is None:
            self._p(f"`{format_key(key)}': not found")
        else:
            self._p(f"`{format_key(key)}' is `{format_key(val)}'")

    def _cmd_set(self, args):
        self._need_write()
        key, val = parse_key(args[0]), parse_key(args[1])
        self._run(lambda tr: tr.set(key, val))
        self._p("Committed" if self.tr is None else "Staged")

    def _cmd_clear(self, args):
        self._need_write()
        key = parse_key(args[0])
        self._run(lambda tr: tr.clear(key))
        self._p("Committed" if self.tr is None else "Staged")

    def _cmd_clearrange(self, args):
        self._need_write()
        b, e = parse_key(args[0]), parse_key(args[1])
        self._run(lambda tr: tr.clear_range(b, e))
        self._p("Committed" if self.tr is None else "Staged")

    def _cmd_getrange(self, args, keys_only=False):
        b = parse_key(args[0])
        e = parse_key(args[1]) if len(args) > 1 else b"\xff"
        limit = int(args[2]) if len(args) > 2 else 25
        rows = self._run(lambda tr: tr.get_range(b, e, limit=limit))
        self._p("Range limited to {} keys".format(limit))
        for k, v in rows:
            if keys_only:
                self._p(f"`{format_key(k)}'")
            else:
                self._p(f"`{format_key(k)}' is `{format_key(v)}'")

    def _cmd_getrangekeys(self, args):
        self._cmd_getrange(args, keys_only=True)

    def _cmd_begin(self, args):
        if self.tr is not None:
            self._p("ERROR: Already in a transaction")
            return
        self.tr = self.db.create_transaction()
        self._p("Transaction started")

    def _cmd_commit(self, args):
        if self.tr is None:
            self._p("ERROR: No active transaction")
            return
        # a failed commit still ends the transaction (real fdbcli resets
        # on commit failure — later commands must not keep hitting the
        # dead transaction's used-commit state)
        try:
            self.tr.commit()
        except BaseException:
            self.tr = None
            raise
        self._p(f"Committed ({self.tr.get_committed_version()})")
        self.tr = None

    def _cmd_reset(self, args):
        if self.tr is not None:
            self.tr.reset()
        self.tr = None
        self._p("Transaction reset")

    def _cmd_getversion(self, args):
        self._p(str(self.db.create_transaction().get_read_version()))

    def _cmd_status(self, args):
        st = self.db.status()
        if args and args[0] == "json":
            self._p(json.dumps(st, indent=2))
            return
        c = st["cluster"]
        w = c["workload"]["transactions"]
        self._p(
            "Configuration:",
            f"  Coordinators        - {c.get('coordinators', 1)}",
            f"  Resolvers           - {c['resolvers']} "
            f"(backend: {c['resolver_backend']})",
            f"  Storage servers     - {c['storage_servers']}",
            f"  Shards              - {c.get('data', {}).get('shards', 1)}",
        )
        # multi-region replication (only when configured: an
        # unconfigured cluster's status output stays unchanged)
        reg = c.get("regions") or {}
        if reg.get("configured"):
            self._p(
                f"  Regions             - {reg['primary']} -> "
                f"{reg['remote']} ({reg['satellite_mode']}, "
                f"{reg['satellites']} satellite"
                f"{'s' if reg['satellites'] != 1 else ''}, "
                f"active: {reg['active']})",
                f"  Replication lag     - "
                f"{reg['replication_lag_versions']} versions / "
                f"{reg['replication_lag_ms']} ms"
                + ("" if reg["connected"] else "  [DISCONNECTED]"),
            )
        self._p(
            "Workload:",
            f"  Started             - {w['started']['counter']}",
            f"  Committed           - {w['committed']['counter']}",
            f"  Conflicted          - {w['conflicted']['counter']}",
        )
        # live rates from the metrics-history windows (the delta between
        # the two most recent samples), not lifetime-counter averages —
        # a cluster that was busy an hour ago and idle now shows ~0
        from foundationdb_tpu.utils import timeseries as ts_mod

        hist = c.get("history") or {}
        if hist.get("windows", 0) >= 2:
            rates = ts_mod.live_rates(hist)
            self._p(
                "Rates (last history window):",
                f"  Committed tx/s      - "
                f"{rates.get('txn_committed', 0.0)}",
                f"  Reads/s             - {rates.get('reads', 0.0)}",
                f"  Conflicts/s         - "
                f"{rates.get('txn_conflicted', 0.0)}",
            )
        # latency rollups from the metrics subsystem (ref: the latency
        # probe section of fdbcli status)
        roll = c.get("metrics", {}).get("rollups", {})
        if roll.get("commit_spans"):
            self._p(
                "Latency (ms):",
                f"  Commit p50 / p99    - "
                f"{roll['commit_latency_p50_ms']} / "
                f"{roll['commit_latency_p99_ms']}",
                f"  GRV p99             - {roll['grv_latency_p99_ms']}",
                f"  Hottest stage       - {roll.get('hottest_stage')}",
            )
        # conflict repair + abort-aware batch scheduling (shown once
        # either subsystem has done anything, so a default-off cluster's
        # status stays unchanged)
        if roll.get("repair_attempts") or roll.get("sched_reordered") \
                or roll.get("sched_deferred"):
            self._p(
                "Conflict management:",
                f"  Repairs             - "
                f"{roll.get('repair_commits', 0)} committed / "
                f"{roll.get('repair_attempts', 0)} attempted "
                f"({roll.get('repair_fallbacks', 0)} fell back)",
                f"  Scheduler           - "
                f"{roll.get('sched_reordered', 0)} reordered, "
                f"{roll.get('sched_deferred', 0)} deferred",
            )
        self._p(
            f"Generation: {c['generation']}",
            f"Latest version: {c['latest_version']}",
        )

    def _cmd_exclude(self, args):
        """Ref: fdbcli exclude — drain a storage so it can be removed."""
        cluster = self.db._cluster
        if not args:
            ex = sorted(cluster.dd.excluded)
            if not ex:
                self._p("No storages are excluded.")
            for sid in ex:
                state = "drained" if cluster.storage_drained(sid) else "draining"
                self._p(f"  storage {sid}: {state}")
            return
        sid = int(args[0])
        if not 0 <= sid < len(cluster.storages):
            self._p(f"ERROR: no storage {sid}")
            return
        cluster.exclude_storage(sid)
        state = "drained" if cluster.storage_drained(sid) else "draining"
        self._p(f"Storage {sid} excluded ({state}).")

    def _cmd_include(self, args):
        cluster = self.db._cluster
        if not args:
            self._p("ERROR: include requires a storage id")
            return
        sid = int(args[0])
        if not 0 <= sid < len(cluster.storages):
            self._p(f"ERROR: no storage {sid}")
            return
        cluster.include_storage(sid)
        self._p(f"Storage {sid} included.")

    def _cmd_lock(self, args):
        """Ref: fdbcli lock — block non-lock-aware commits (1038)."""
        uid = args[0].encode() if args else b"fdbcli-lock"
        self.db._cluster.lock_database(uid)
        self._p(f"Database locked ({uid.decode()}).")

    def _cmd_unlock(self, args):
        self.db._cluster.unlock_database()
        self._p("Database unlocked.")

    def _cmd_consistencycheck(self, args):
        """Ref: fdbcli consistencycheck — audit replica agreement across
        every shard's team at the current committed version (the same
        batch-compare core the continuous scan walks)."""
        errors = self.db._cluster.consistency_check()
        if not errors:
            self._p("Consistency check: PASS")
        else:
            self._p(f"Consistency check: FAIL ({len(errors)} errors)")
            for e in errors[:20]:
                self._p(f"  {e}")
        # the continuous scan role's stats ride along when it is live
        from foundationdb_tpu.txn import specialkeys as sk

        try:
            doc = json.loads(
                self._run(lambda tr: tr.get(sk.CONSISTENCY_SCAN))
            )
        except (FDBError, ValueError, TypeError):
            return
        if doc.get("enabled"):
            self._print_scan(doc)

    def _print_scan(self, doc):
        state = "enabled" if doc.get("enabled") else "disabled"
        self._p(
            f"Consistency scan: {state}",
            f"  Rounds complete     - {doc.get('round', 0)} "
            f"(last {doc.get('last_round_ms', 0.0)} ms)",
            f"  Progress            - {doc.get('progress_pct', 0.0)}% "
            f"({doc.get('batches', 0)} batches)",
            f"  Scanned             - {doc.get('keys_scanned', 0)} keys "
            f"/ {doc.get('bytes_scanned', 0)} bytes",
            f"  Inconsistencies     - {doc.get('inconsistencies', 0)} "
            f"({doc.get('reread_saves', 0)} dismissed by re-read)",
        )
        for e in (doc.get("errors") or [])[:5]:
            self._p(f"  ERROR {e}")

    def _cmd_scan(self, args):
        """Continuous consistency scan (server/consistencyscan.py):
        ``scan status [json]`` prints the background auditor's document
        — read through the ``\\xff\\xff/status/consistency_scan``
        special key so the same command works against remote clusters —
        and ``scan on|off`` flips the scanner's kill switch."""
        from foundationdb_tpu.txn import specialkeys as sk

        sub = args[0] if args else "status"
        if sub in ("on", "off"):
            doc = self.db._cluster.set_consistency_scan(sub == "on")
            state = "enabled" if doc.get("enabled") else "disabled"
            self._p(f"Consistency scan {state}.")
            return
        if sub != "status":
            self._p(f"ERROR: unknown scan subcommand `{sub}'")
            return
        doc = json.loads(
            self._run(lambda tr: tr.get(sk.CONSISTENCY_SCAN))
        )
        if len(args) > 1 and args[1] == "json":
            self._p(json.dumps(doc, indent=2, sort_keys=True))
            return
        self._print_scan(doc)

    def _cmd_configure(self, args):
        """Ref: fdbcli `configure` → changeConfig. Supported:
        commit_proxies=N / resolvers=N (a txn-system recovery installs
        the new fleet size over the same storage and logs) and
        regions=<json>|off (multi-region replication: the JSON names
        primary/remote region ids, satellite count, and sync|async
        satellite mode — see server/region.py RegionConfig)."""
        kw = {}
        for a in args:
            k, _, v = a.partition("=")
            if k in ("commit_proxies", "proxies") and v:
                kw["commit_proxies"] = int(v)
            elif k == "resolvers" and v:
                kw["resolvers"] = int(v)
            elif k == "regions" and v:
                # "off" detaches; anything else must be the region
                # JSON — validation (and the typo errors) belong to
                # RegionConfig.parse, not the shell
                kw["regions"] = v
            else:
                self._p(f"ERROR: unsupported configure option `{a}'")
                return
        if not kw:
            self._p("ERROR: nothing to configure")
            return
        self.db._cluster.configure(**kw)
        self._p("Configuration changed")

    def _cmd_option(self, args):
        self._p("Option enabled for all transactions")

    def _open_cluster(self, cluster_file):
        if self._open_fn is not None:
            return self._open_fn(cluster_file)
        import foundationdb_tpu as fdb

        return fdb.open(cluster_file=cluster_file)

    def _mc(self):
        from foundationdb_tpu.layers.metacluster import Metacluster

        if self._metacluster is None:
            self._metacluster = Metacluster(self.db)
        return self._metacluster

    def _cmd_metacluster(self, args):
        """Ref: the fdbcli `metacluster` command family
        (MetaclusterCommands.actor.cpp): create the management cluster,
        register/attach/remove data clusters, place and move tenants."""
        from foundationdb_tpu.layers.metacluster import Metacluster

        sub = args[0] if args else "status"
        if sub == "create":
            self._metacluster = Metacluster.create(
                self.db, parse_key(args[1]) if len(args) > 1 else b"meta")
            self._p("The metacluster has been created")
        elif sub == "register":
            name = parse_key(args[1])
            capacity = int(args[3]) if len(args) > 3 else 100
            self._mc().register_data_cluster(
                name, self._open_cluster(args[2]), capacity=capacity)
            self._p(f"The data cluster `{args[1]}' has been registered")
        elif sub == "attach":
            self._mc().attach_data_cluster(
                parse_key(args[1]), self._open_cluster(args[2]))
            self._p(f"The data cluster `{args[1]}' has been attached")
        elif sub == "remove":
            name = parse_key(args[1])
            mc = self._mc()
            if name not in mc.databases and len(args) > 2:
                mc.attach_data_cluster(name, self._open_cluster(args[2]))
            if name not in mc.databases:
                # removing unattached would clear the registry row but
                # leave the data-side mark, bricking re-registration
                self._p("ERROR: data cluster not attached — use "
                        "`metacluster remove NAME CLUSTER_FILE'")
                return
            mc.remove_data_cluster(name)
            self._p(f"The data cluster `{args[1]}' has been removed")
        elif sub == "status":
            mc = self._mc()
            clusters = mc.list_data_clusters()
            tenants = mc.list_tenants()
            self._p(f"metacluster: {len(clusters)} data cluster(s), "
                    f"{len(tenants)} tenant(s)")
            for name, meta in sorted(clusters.items()):
                self._p(f"  {format_key(name)}: "
                        f"{meta['tenants']}/{meta['capacity']} tenants")
        elif sub == "tenant":
            op = args[1]
            mc = self._mc()
            if op == "create":
                cluster = mc.create_tenant(parse_key(args[2]))
                self._p(f"The tenant `{args[2]}' has been created on "
                        f"`{format_key(cluster)}'")
            elif op == "delete":
                mc.delete_tenant(parse_key(args[2]))
                self._p(f"The tenant `{args[2]}' has been deleted")
            elif op == "list":
                for name, a in sorted(mc.list_tenants().items()):
                    owner = format_key(a["cluster"].encode("latin-1"))
                    self._p(f"  {format_key(name)} -> {owner}"
                            + ("" if a["state"] == "ready"
                               else f" ({a['state']})"))
            elif op == "move":
                mc.move_tenant(parse_key(args[2]), parse_key(args[3]))
                self._p(f"The tenant `{args[2]}' has been moved to "
                        f"`{args[3]}'")
            elif op == "resume":
                mc.resume_move(parse_key(args[2]))
                self._p(f"The tenant `{args[2]}' move has been resumed")
            else:
                self._p(f"ERROR: unknown metacluster tenant op `{op}'")
        else:
            self._p(f"ERROR: unknown metacluster subcommand `{sub}'")

    def _cmd_tenant(self, args):
        from foundationdb_tpu.layers.tenant import TenantManagement as TM

        sub = args[0]
        if sub == "create":
            TM.create_tenant(self.db, parse_key(args[1]))
            self._p(f"The tenant `{args[1]}' has been created")
        elif sub == "delete":
            TM.delete_tenant(self.db, parse_key(args[1]))
            self._p(f"The tenant `{args[1]}' has been deleted")
        elif sub == "list":
            for name, _meta in TM.list_tenants(self.db):
                self._p(format_key(name))
        elif sub == "get":
            names = [n for n, _ in TM.list_tenants(self.db)]
            key = parse_key(args[1])
            if key in names:
                self._p(f"The tenant `{args[1]}' exists")
                quota = TM.get_tenant_quota(self.db, key)
                if quota is not None:
                    self._p(f"  quota: {quota} tps")
                group = TM.get_tenant_group(self.db, key)
                if group is not None:
                    self._p(f"  group: {format_key(group)}")
            else:
                self._p(f"ERROR: Tenant `{args[1]}' does not exist")
        elif sub == "mode":
            # ref: the tenant_mode configuration knob
            if len(args) > 1:
                TM.set_tenant_mode(self.db, args[1])
                self._p(f"Tenant mode set to `{args[1]}'")
            else:
                self._p(TM.get_tenant_mode(self.db))
        elif sub == "quota":
            # tenant quota NAME [TPS|clear] (ref: fdbcli quota)
            key = parse_key(args[1])
            if len(args) > 2:
                tps = None if args[2] == "clear" else float(args[2])
                TM.set_tenant_quota(self.db, key, tps)
                self._p(
                    f"Quota for `{args[1]}' "
                    + ("cleared" if tps is None else f"set to {tps} tps")
                )
            else:
                quota = TM.get_tenant_quota(self.db, key)
                self._p("no quota" if quota is None else f"{quota} tps")
        else:
            raise ValueError(f"unknown tenant subcommand {sub}")

    def _cmd_tracing(self, args):
        """Distributed tracing config, wired through the
        ``\\xff\\xff/tracing/`` special-key space (so the same command
        works against in-process and remote clusters): ``tracing
        status`` reads the module rows; ``on`` / ``off`` / ``sample
        RATE`` write them (applied at commit like other management
        writes)."""
        from foundationdb_tpu.txn import specialkeys as sk

        sub = args[0] if args else "status"
        if sub == "status":
            def read(tr):
                return (tr.get(sk.TRACING_ENABLED),
                        tr.get(sk.TRACING_RATE))

            enabled, rate = self._run(read)
            state = "on" if enabled == b"1" else "off"
            self._p(f"Tracing: {state} (sample rate "
                    f"{(rate or b'0').decode()})")
        elif sub == "on":
            self._run(lambda tr: tr.set(sk.TRACING_ENABLED, b"1"))
            self._p("Tracing enabled")
        elif sub == "off":
            self._run(lambda tr: tr.set(sk.TRACING_ENABLED, b"0"))
            self._p("Tracing disabled")
        elif sub == "sample":
            if len(args) < 2:
                raise ValueError("usage: tracing sample RATE")
            rate = args[1]
            float(rate)  # malformed rates fail HERE, not at commit
            self._run(lambda tr: tr.set(sk.TRACING_RATE, rate.encode()))
            self._p(f"Tracing sample rate set to {rate}")
        else:
            raise ValueError(
                "usage: tracing status | on | off | sample RATE"
            )

    def _cmd_throttle(self, args):
        """Ref: fdbcli throttle — per-tag rate limits. ``throttle on
        tag TAG RATE`` / ``throttle off tag TAG`` / ``throttle list``."""
        cluster = self.db._cluster
        if args and args[0] == "list":
            # Read through status json so a RemoteCluster (which has no
            # local ratekeeper attribute) reports the truth instead of
            # always printing "no throttled tags".
            tags = (self.db.status().get("cluster", {})
                    .get("qos", {}).get("throttled_tags", {}) or {})
            if not tags:
                self._p("There are no throttled tags")
            for tag, tps in sorted(tags.items()):
                self._p(f"  {tag}: {tps} tps")
        elif len(args) >= 4 and args[0] == "on" and args[1] == "tag":
            cluster.set_tag_quota(args[2], float(args[3]))
            self._p(f"Tag `{args[2]}' throttled at {args[3]} tps")
        elif len(args) >= 3 and args[0] == "off" and args[1] == "tag":
            cluster.set_tag_quota(args[2], None)
            self._p(f"Tag `{args[2]}' unthrottled")
        else:
            raise ValueError("usage: throttle list | on tag TAG TPS | "
                             "off tag TAG")

    def _cmd_top(self, args):
        """Workload attribution (ref: fdbcli's hot-range tooling around
        StorageMetrics): top-K key ranges by conflict/read/write heat
        plus per-tag busyness, read through the
        ``\\xff\\xff/metrics/hot_ranges`` special key so the same
        command works against remote clusters."""
        from foundationdb_tpu.txn import specialkeys as sk

        dims = ("conflict", "read", "write")
        if args and args[0] in dims:
            dims = (args[0],)
            args = args[1:]
        k = int(args[0]) if args else 5
        doc = json.loads(self._run(lambda tr: tr.get(sk.HOT_RANGES)))
        if doc.get("sampling") is False:
            self._p("Workload sampling is disabled")
            return
        ranges = doc.get("hot_ranges", {})
        for dim in dims:
            rows = sorted(ranges.get(dim, ()),
                          key=lambda r: -r["heat"])[:k]
            self._p(f"Hot ranges ({dim}):")
            if not rows:
                self._p("  (none sampled)")
                continue
            for r in rows:
                begin = format_key(r["begin"].encode("latin-1"))
                end = (format_key(r["end"].encode("latin-1"))
                       if r["end"] is not None else "<end>")
                self._p(f"  [{begin}, {end}): {r['heat']}")
        tags = doc.get("tags", {})
        if tags:
            self._p("Tags:")
            for tag, row in sorted(tags.items()):
                fields = ", ".join(
                    f"{f}={row[f]}" for f in
                    ("started", "committed", "conflicted", "too_old",
                     "busyness", "limit_tps") if f in row
                )
                self._p(f"  {tag}: {fields}")


    def _cmd_profile(self, args):
        """Device-path execution profile (ref: fdbcli's profiler
        commands over flow/Profiler.actor.cpp): the resolver fleet's
        dispatch accounting — pad/bucket occupancy, compile events,
        staging reuse, fallback causes, per-lane walls — read through
        the ``\\xff\\xff/metrics/device`` special key so the same
        command works against remote clusters."""
        from foundationdb_tpu.txn import specialkeys as sk

        doc = json.loads(self._run(lambda tr: tr.get(sk.DEVICE)))
        if args and args[0] == "json":
            self._p(json.dumps(doc, indent=2, sort_keys=True))
            return
        if not doc.get("enabled", True):
            self._p("Device profiling is disabled")
        agg = doc.get("aggregate", {})
        self._p(
            "Device profile (aggregate):",
            f"  dispatches: {agg.get('dispatches', 0)}"
            f"  recompiles: {agg.get('recompiles', 0)}",
            f"  pad_waste_pct: {agg.get('pad_waste_pct', 0.0)}"
            f"  bucket_histogram: {agg.get('bucket_histogram', {})}",
            f"  staging_reuse_rate: {agg.get('staging_reuse_rate', 0.0)}"
            f"  transfer_bytes: {agg.get('transfer_bytes', 0)}",
            f"  dispatch_wall_ms: {agg.get('dispatch_wall_ms', 0.0)}"
            f"  verdict_reduce_wall_ms: "
            f"{agg.get('verdict_reduce_wall_ms', 0.0)}",
            f"  fallback_causes: {agg.get('fallback_causes', {})}",
        )
        for r in doc.get("resolvers", ()):
            lanes = r.get("lanes", 0)
            lane_note = (
                f" lanes={lanes} lane_skew_pct={r.get('lane_skew_pct')}"
                if lanes > 1 else ""
            )
            self._p(
                f"  resolver {r.get('id')}: "
                f"dispatches={r.get('dispatches')} "
                f"pad_waste_pct={r.get('pad_waste_pct')} "
                f"recompiles={r.get('recompiles')}{lane_note}"
            )


    def _cmd_history(self, args):
        """Metrics history (ref: the TDMetric channels fdbcli status
        reads back over time): the retention layer's bounded windows —
        counter rates, gauge rollups, latency p99 trajectories, and the
        verdict timeline — read through the
        ``\\xff\\xff/metrics/history`` special key so the same command
        works against remote clusters. With METRIC, prints that one
        series' full trajectory."""
        from foundationdb_tpu.txn import specialkeys as sk

        doc = json.loads(self._run(lambda tr: tr.get(sk.HISTORY)))
        if args and args[0] == "json":
            self._p(json.dumps(doc, indent=2, sort_keys=True))
            return
        if not doc.get("enabled", True):
            self._p("Metrics history is disabled")
        series = doc.get("series", {})
        if args:
            name = args[0]
            rows = series.get("counters", {}).get(name)
            if rows is not None:
                for r in rows:
                    self._p(f"  t={r['t']}: rate={r['rate']}/s "
                            f"(total {r['total']})")
                return
            g = series.get("gauges", {}).get(name)
            if g is not None:
                for r in g.get("windows", ()):
                    self._p(f"  t={r['t']}: {r['value']}")
                self._p(f"  last={g['last']} min={g['min']} "
                        f"max={g['max']}")
                return
            rows = series.get("latency_p99_ms", {}).get(name)
            if rows is not None:
                for r in rows:
                    self._p(f"  t={r['t']}: p99={r['p99_ms']} ms")
                return
            known = sorted(
                list(series.get("counters", {}))
                + list(series.get("gauges", {}))
                + list(series.get("latency_p99_ms", {})))
            self._p(f"ERROR: no metric `{name}'. Known: "
                    + ", ".join(known))
            return
        self._p(
            f"History: {doc.get('windows', 0)} window(s) retained "
            f"of {doc.get('capacity', 0)} "
            f"(cadence {doc.get('cadence_s', 0.0)}s, "
            f"{doc.get('windows_collected', 0)} collected)"
        )
        counters = series.get("counters", {})
        if counters:
            self._p("Rates (last window, /s):")
            for name, rows in sorted(counters.items()):
                if rows:
                    self._p(f"  {name:<22}- {rows[-1]['rate']}")
        lats = series.get("latency_p99_ms", {})
        if lats:
            self._p("Latency p99 (last window, ms):")
            for name, rows in sorted(lats.items()):
                if rows:
                    self._p(f"  {name:<22}- {rows[-1]['p99_ms']}")
        for a in doc.get("trend_alerts", ()):
            self._p(f"  TREND {a['name']}: {a['from_ms']} -> "
                    f"{a['to_ms']} ms (+{a['rise_pct']}% over "
                    f"{a['windows']} windows)")
        for tr_ in doc.get("transitions", ()):
            self._p(f"  verdict @ t={tr_['t']}: {tr_['from']} -> "
                    f"{tr_['to']}")
        fl = doc.get("flight", {})
        if fl.get("dumps"):
            self._p(f"Flight recorder: {fl['dumps']} dump(s), last "
                    f"triggers {fl.get('last_triggers')}")

    def _cmd_doctor(self, args):
        """Cluster doctor (ref: the health checks operators run through
        fdbcli status details): verdict, reasons, probe latency bands,
        recovery timeline, and SLO alerts — read through the
        ``\\xff\\xff/status/health`` special key so the same command
        works against remote clusters."""
        from foundationdb_tpu.tools import doctor as doctor_mod
        from foundationdb_tpu.txn import specialkeys as sk

        doc = json.loads(self._run(lambda tr: tr.get(sk.HEALTH)))
        if args and args[0] == "json":
            self._p(json.dumps(doc, indent=2, sort_keys=True))
            return
        alerts, verdict = doctor_mod.check(doc)
        probe = doc.get("probe", {})
        rec = doc.get("recovery", {})
        lag = doc.get("lag", {})
        self._p(
            f"Cluster health: {verdict}",
            f"  Probes              - {probe.get('probes', 0)} "
            f"({probe.get('failures', 0)} failed)",
            f"  GRV p99 (ms)        - "
            f"{probe.get('grv', {}).get('p99_ms', 0.0)}",
            f"  Commit p99 (ms)     - "
            f"{probe.get('commit', {}).get('p99_ms', 0.0)}",
            f"  Recoveries          - {rec.get('count', 0)} "
            f"(last {rec.get('last_recovery_ms', 0.0)} ms, "
            f"generation {rec.get('generation', 0)})",
            f"  Durability lag      - "
            f"{lag.get('durability_lag_versions_max', 0)} versions",
        )
        for m in doc.get("messages", ()):
            self._p(f"  message: {m['name']} — {m['description']}")
        for a in alerts:
            self._p(f"  ALERT {a}")
        if not alerts:
            self._p("  No alerts.")


def main(argv=None):
    import argparse

    from foundationdb_tpu.server.cluster import Cluster

    ap = argparse.ArgumentParser(prog="fdbcli")
    ap.add_argument("--exec", dest="exec_cmds", action="append", default=[])
    ap.add_argument("--wal", default=None, help="WAL path for durability")
    ns = ap.parse_args(argv)

    db = Cluster(wal_path=ns.wal).database()
    cli = Cli(db)
    cli.write_mode = True
    if ns.exec_cmds:
        for c in ns.exec_cmds:
            for sub in c.split(";"):
                cli.run_command(sub.strip())
    else:
        cli.repl()


if __name__ == "__main__":
    main()
