r"""Cluster doctor watchdog: tail health, alert on SLO violations.

Ref parity: the operator loop around fdbcli's status details /
``cluster.messages`` — a watchdog that polls the health document the
cluster already computes (server/health.py) and turns it into
machine-checkable alerts with a nonzero exit code, so CI and chaos
scenarios can chain it::

    python -m foundationdb_tpu.tools.doctor --connect host:4500
    python -m foundationdb_tpu.tools.doctor --status-file status.json
    python -m foundationdb_tpu.tools.doctor --connect host:4500 --watch 0

``check()`` is pure (one health doc + thresholds in, alerts out) — the
sim chaos tests drive it directly against in-process clusters. SLO
thresholds default from the doctor_* knobs (core/options.py) and can be
overridden per flag.
"""

import argparse
import json
import sys
import time

from foundationdb_tpu.core.options import DEFAULT_KNOBS

DEFAULT_THRESHOLDS = {
    "probe_p99_ms": DEFAULT_KNOBS.doctor_probe_p99_ms,
    "recovery_ms": DEFAULT_KNOBS.doctor_recovery_ms,
    "lag_versions": DEFAULT_KNOBS.doctor_lag_versions,
    "region_lag_versions": DEFAULT_KNOBS.doctor_region_lag_versions,
    "failover_ms": DEFAULT_KNOBS.doctor_region_failover_ms,
}


def trend_check(history_doc, windows=None, min_rise_pct=None):
    """Early-warning alerts from the metrics-history document
    (utils/timeseries.py): a probe p99 rising monotonically across
    consecutive windows alerts BEFORE the instant SLO threshold
    breaches. Pure like ``check()`` — same doc, same alerts."""
    from foundationdb_tpu.utils import timeseries as ts_mod

    hits = ts_mod.trend_alerts_from_doc(
        history_doc,
        windows=windows or DEFAULT_KNOBS.doctor_trend_windows,
        min_rise_pct=(min_rise_pct if min_rise_pct is not None
                      else DEFAULT_KNOBS.doctor_trend_min_rise_pct),
    )
    return [
        f"trend: probe {h['name']} p99 rising {h['from_ms']} -> "
        f"{h['to_ms']}ms (+{h['rise_pct']}% over {h['windows']} windows)"
        for h in hits
    ]


def scan_check(scan, max_round_age_s=None):
    """Consistency-scan SLOs (tools/doctor.py --scan): one scan doc in,
    alerts out — pure like ``check()``. Two invariants: confirmed
    inconsistencies must be ZERO (the scanner already dismissed
    split/move artifacts via its live-map re-read, so any survivor is
    real corruption), and the last completed round must be fresher than
    the age bound (a stalled scanner is a blind cluster)."""
    th = (max_round_age_s if max_round_age_s is not None
          else DEFAULT_KNOBS.doctor_scan_max_round_age_s)
    alerts = []
    if not isinstance(scan, dict) or not scan:
        return alerts
    inc = scan.get("inconsistencies", 0) or 0
    if inc:
        alerts.append(
            f"scan: {inc} confirmed replica inconsistencies "
            "(data_inconsistent)"
        )
        for e in (scan.get("errors") or [])[:3]:
            alerts.append(f"scan: {e}")
    if scan.get("enabled"):
        age = scan.get("round_age_s")
        if age is not None and age > th:
            alerts.append(
                f"scan: last completed round is {age}s old, over {th}s"
            )
    return alerts


def extract_scan(doc):
    """Accept a bare scan doc, a full status doc, or its ``cluster``
    section — whichever the source produced."""
    if not isinstance(doc, dict):
        return {}
    if "inconsistencies" in doc:
        return doc
    if "cluster" in doc:
        return doc["cluster"].get("consistency_scan", {})
    return doc.get("consistency_scan", {})


def extract_history(doc):
    """Accept a bare history doc, a full status doc, or its ``cluster``
    section — whichever the source produced."""
    if not isinstance(doc, dict):
        return {}
    if "series" in doc:
        return doc
    if "cluster" in doc:
        return doc["cluster"].get("history", {})
    return doc.get("history", {})


def check(health, thresholds=None):
    """One health document → ``(alerts, verdict)``. Pure and
    deterministic: the same doc and thresholds always yield the same
    alerts, so same-seed sims produce identical doctor output."""
    th = dict(DEFAULT_THRESHOLDS)
    if thresholds:
        th.update({k: v for k, v in thresholds.items() if v is not None})
    alerts = []
    verdict = health.get("verdict", "unknown")
    if verdict != "healthy":
        messages = health.get("messages") or [
            {"name": r, "description": r}
            for r in health.get("reasons", ())
        ]
        if not messages:
            alerts.append(f"{verdict}: cluster is not healthy")
        for m in messages:
            alerts.append(f"{verdict}: {m['name']} — {m['description']}")
    probe = health.get("probe", {})
    for hop in ("grv", "commit"):
        bands = probe.get(hop) or {}
        if bands.get("count") and bands.get("p99_ms", 0) \
                > th["probe_p99_ms"]:
            alerts.append(
                f"slo: probe {hop} p99 {bands['p99_ms']}ms exceeds "
                f"{th['probe_p99_ms']}ms"
            )
    rec = health.get("recovery", {})
    last_ms = rec.get("last_recovery_ms", 0) or 0
    if last_ms > th["recovery_ms"]:
        alerts.append(
            f"slo: last recovery took {last_ms}ms, over "
            f"{th['recovery_ms']}ms"
        )
    lag = health.get("lag", {}).get("durability_lag_versions_max", 0) or 0
    if lag > th["lag_versions"]:
        alerts.append(
            f"slo: storage durability lag {lag} versions exceeds "
            f"{th['lag_versions']}"
        )
    # region SLOs: only meaningful while replication is configured —
    # an unconfigured cluster must never alert on region state
    regions = health.get("regions") or {}
    if regions.get("configured"):
        rlag = regions.get("replication_lag_versions", 0) or 0
        if rlag > th["region_lag_versions"]:
            alerts.append(
                f"slo: region replication lag {rlag} versions exceeds "
                f"{th['region_lag_versions']}"
            )
        if not regions.get("connected", True):
            alerts.append(
                "slo: satellite region disconnected "
                f"(broken={regions.get('broken', False)})"
            )
        fo_ms = regions.get("last_failover_ms", 0) or 0
        if fo_ms > th["failover_ms"]:
            alerts.append(
                f"slo: last region failover took {fo_ms}ms, over "
                f"{th['failover_ms']}ms"
            )
    return alerts, verdict


def extract_health(doc):
    """Accept a bare health doc, a full status doc, or its ``cluster``
    section — whichever the source produced."""
    if not isinstance(doc, dict):
        return {}
    if "verdict" in doc:
        return doc
    if "cluster" in doc:
        return doc["cluster"].get("health", {})
    return doc.get("health", {})


def _report(health, alerts, verdict, as_json, out):
    if as_json:
        print(json.dumps(
            {"verdict": verdict, "alerts": alerts,
             "reasons": health.get("reasons", []),
             "recovery_count": health.get("recovery", {}).get("count", 0)},
            sort_keys=True), file=out)
        return
    probe = health.get("probe", {})
    rec = health.get("recovery", {})
    print(
        f"doctor: {verdict} "
        f"(probes={probe.get('probes', 0)} "
        f"failures={probe.get('failures', 0)} "
        f"recoveries={rec.get('count', 0)} "
        f"last_recovery_ms={rec.get('last_recovery_ms', 0)})",
        file=out,
    )
    for a in alerts:
        print(f"  ALERT {a}", file=out)


def main(argv=None, out=None, sleep=time.sleep):
    out = out if out is not None else sys.stdout
    ap = argparse.ArgumentParser(
        prog="python -m foundationdb_tpu.tools.doctor",
        description="Watchdog over the cluster.health document.",
    )
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--connect", metavar="HOST:PORT",
                     help="poll a remote cluster's health RPC")
    src.add_argument("--status-file", metavar="PATH",
                     help="re-read a health/status JSON file each round")
    ap.add_argument("--watch", type=int, default=None, metavar="N",
                    help="poll N rounds (0 = forever); default: once")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="seconds between watch rounds")
    ap.add_argument("--probe-p99-ms", type=float, default=None)
    ap.add_argument("--recovery-ms", type=float, default=None)
    ap.add_argument("--lag-versions", type=int, default=None)
    ap.add_argument("--region-lag-versions", type=int, default=None)
    ap.add_argument("--failover-ms", type=float, default=None)
    ap.add_argument("--trend", action="store_true",
                    help="also scan the metrics history for monotone "
                         "probe-p99 rises (alerts before the SLO breaks)")
    ap.add_argument("--trend-windows", type=int, default=None)
    ap.add_argument("--trend-min-rise-pct", type=float, default=None)
    ap.add_argument("--scan", action="store_true",
                    help="also check the continuous consistency scan "
                         "(inconsistencies == 0, round age bound)")
    ap.add_argument("--scan-max-round-age-s", type=float, default=None)
    ap.add_argument("--json", action="store_true", dest="as_json")
    ns = ap.parse_args(argv)
    thresholds = {
        "probe_p99_ms": ns.probe_p99_ms,
        "recovery_ms": ns.recovery_ms,
        "lag_versions": ns.lag_versions,
        "region_lag_versions": ns.region_lag_versions,
        "failover_ms": ns.failover_ms,
    }

    remote = None
    if ns.connect:
        from foundationdb_tpu.rpc.service import RemoteCluster

        remote = RemoteCluster([ns.connect])

    def poll():
        if remote is not None:
            return remote.health_status()
        with open(ns.status_file) as f:
            return extract_health(json.load(f))

    def poll_history():
        if remote is not None:
            return remote.history_status()
        with open(ns.status_file) as f:
            return extract_history(json.load(f))

    def poll_scan():
        if remote is not None:
            return remote.consistency_scan_status()
        with open(ns.status_file) as f:
            return extract_scan(json.load(f))

    try:
        rounds = 1 if ns.watch is None else ns.watch
        n = 0
        alerts, verdict = [], "unknown"
        while True:
            health = poll()
            alerts, verdict = check(health, thresholds)
            if ns.trend:
                alerts = alerts + trend_check(
                    poll_history(), ns.trend_windows,
                    ns.trend_min_rise_pct)
            if ns.scan:
                alerts = alerts + scan_check(
                    poll_scan(), ns.scan_max_round_age_s)
            _report(health, alerts, verdict, ns.as_json, out)
            n += 1
            if rounds and n >= rounds:
                break
            sleep(ns.interval)
    finally:
        if remote is not None:
            remote.close()
    # the chainable contract: nonzero exactly when the LAST round
    # alerted, so `doctor && next-step` gates on current health
    return 1 if alerts else 0


if __name__ == "__main__":
    sys.exit(main())
