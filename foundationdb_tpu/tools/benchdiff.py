"""Bench-trajectory differ: per-metric deltas across BENCH_r* rounds.

The bench driver captures one ``BENCH_rNN.json`` per round with the
shape ``{n, cmd, rc, tail, parsed}`` — ``parsed`` is the driver's read
of the final stdout line (the compact summary or the headline), and
``tail`` is a BOUNDED capture of the last ~2KB of stdout, which can be
cut MID-LINE at the front. This tool turns a sequence of such rounds
into an aligned per-metric trajectory: first/last/delta/% rows, with
regression flags oriented by each metric's polarity (throughput up is
good; latency, pad waste, recompiles, lane skew up is bad). It is the
reader for the device-path profiler fields (pad_waste_pct,
bucket_histogram totals, recompiles, fallback_causes, lane_skew_pct)
that bench.py now stamps on every e2e line, and for the schema_rev /
git_rev provenance header — but it diffs ANY numeric field it finds,
so older rounds (pre-profiler, pre-provenance) align with explicit
"n/a" cells rather than KeyErrors.

Usage::

    python -m foundationdb_tpu.tools.benchdiff BENCH_r01.json BENCH_r05.json
    python -m foundationdb_tpu.tools.benchdiff --json BENCH_r*.json
"""

import json
import os
import sys

NA = "n/a"

# Metric polarity by substring, checked in order (first hit wins):
# LOWER_BETTER before HIGHER_BETTER so e.g. "conflict_rate" resolves
# lower-better even though bare "rate" names lean higher-better.
LOWER_BETTER = (
    "_ms", "overhead_pct", "conflict_rate", "pad_waste", "lane_skew",
    "recompiles", "aborted", "fallback_causes", "backlog",
    # static-analysis debt + runtime lock-order witness: any growth is
    # a regression ("lockdep_overhead_pct" already resolves via
    # "overhead_pct" above; "flowlint" also covers flowlint_by_rule.*)
    "flowlint", "lockdep_cycles",
    # cluster doctor (ISSUE 13): probe_grv_p99_ms / probe_commit_p99_ms
    # / last_recovery_ms already resolve lower-better via "_ms" above;
    # more recoveries, failed probes, admission denials, deeper queues,
    # and durability lag are all regressions
    "recovery_count", "probe_failures", "admit_denied", "queue_depth",
    "lag_versions",
    # multi-region replication: replication_lag_ms already resolves via
    # "_ms", but the version-denominated lag and any growth in failover
    # count or failover duration are regressions too ("failover" covers
    # region_failovers and last_failover_ms alike)
    "replication_lag", "failover",
    # robustness stack (ISSUE 15): more RPC deadline expiries, more
    # endpoints marked failed, or more backoff sleeps taken on a
    # healthy run are regressions ("robustness_overhead_pct" already
    # resolves via "overhead_pct" above)
    "rpc_timeouts", "endpoints_failed", "backoff_retries",
    # fused Pallas scan kernel (ISSUE 18): the per-batch kernel step
    # wall ("kernel_step_ms" also resolves via "_ms" — this pins the
    # intent if the unit ever changes) and any pallas→jnp retries
    # recorded by the executed-route ledger are regressions
    "kernel_step", "_fallbacks",
    # flight recorder (ISSUE 19): more black-box dumps during a bench
    # run means more verdict flaps / recoveries / SLO breaches —
    # a regression ("history_overhead_pct" already resolves via
    # "overhead_pct"; "commit_rate_trend" resolves higher-better via
    # "commit_rate" below, which is the intent: a decaying trajectory
    # shrinking toward 0 is the regression signature)
    "flight_dumps",
    # continuous consistency scan (ISSUE 20): any confirmed replica
    # inconsistency is a regression outright ("scan_round_ms" /
    # "scan_last_round_ms" already resolve lower-better via "_ms";
    # "scan_overhead_pct" via "overhead_pct"). NOTE: keep bare
    # "scan_round" OUT of this tuple — it would shadow the
    # higher-better "scan_rounds" below, since LOWER_BETTER wins ties
    "scan_inconsistencies",
)
HIGHER_BETTER = (
    "txns_per_sec", "value", "vs_baseline", "speedup", "reuse_rate",
    "repair_rate", "commit_rate", "pipeline_depth", "configs.",
    # read multiplexing (ISSUE 11): more reads per RPC and bigger
    # batch-size percentiles mean better coalescing ("read_batch_p99_ms"
    # — the serve latency — still resolves lower-better via "_ms" above)
    "coalesce_rate", "read_batch_p",
    # sharded resolve (ISSUE 16): the summary metric's value is
    # resolved txns/sec — more is better ("sharded_speedup" and
    # "lane_skew_pct" already resolve via "speedup" / "lane_skew")
    "shard_smoke",
    # fault coverage (ISSUE 17): firing MORE of the enumerated fault
    # sites under chaos is better exploration; fault_sites_total stays
    # neutral (the table growing is neither good nor bad per se)
    "fault_sites_fired", "fault_coverage",
    # fused Pallas scan kernel (ISSUE 18): the chip-resident resolve
    # rate — the 650k→1M headline — is higher-better
    "device_kernel",
    # metrics history (ISSUE 19): retaining more windows over the same
    # run means the collector kept cutting on cadence — fewer would
    # mean stalls or a silently disabled collector
    "history_windows",
    # continuous consistency scan (ISSUE 20): more completed rounds and
    # more keyspace covered over the same run mean a healthier auditor
    # ("scan_inconsistencies" resolves lower-better above, FIRST — it
    # must never ride these substrings)
    "scan_rounds", "scan_progress",
)
# relative change below this is measurement noise, not a trend
REGRESSION_THRESHOLD_PCT = 5.0


def polarity(key):
    """+1 higher-better, -1 lower-better, 0 unknown (never flagged)."""
    for s in LOWER_BETTER:
        if s in key:
            return -1
    for s in HIGHER_BETTER:
        if s in key:
            return +1
    return 0


def _last_json_line(tail):
    """The last complete JSON-object line of a bounded stdout tail.
    The capture window can cut the front line mid-object (observed in
    BENCH_r04: ONE front-cut line), so walk from the end and take the
    first line that parses to a dict; None when nothing does."""
    for ln in reversed((tail or "").splitlines()):
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            doc = json.loads(ln)
        except ValueError:
            continue
        if isinstance(doc, dict):
            return doc
    return None


def load_round(path):
    """One round file → ``{name, n, rc, doc, note}``. ``doc`` is the
    best available bench line: the driver's ``parsed`` when it is a
    dict, else the last complete JSON line of ``tail``, else None
    (``note`` says why). Bare bench-line dicts (no ``tail``/``parsed``
    wrapper) are accepted as their own doc, so the tool also diffs raw
    ``bench.py`` output saved by hand."""
    with open(path) as f:
        raw = json.load(f)
    name = os.path.basename(path)
    if not isinstance(raw, dict):
        return {"name": name, "n": None, "rc": None, "doc": None,
                "note": "not a JSON object"}
    if "parsed" not in raw and "tail" not in raw:
        return {"name": name, "n": raw.get("n"), "rc": raw.get("rc"),
                "doc": raw, "note": "bare bench line"}
    doc = raw.get("parsed") if isinstance(raw.get("parsed"), dict) \
        else None
    note = "parsed"
    if doc is None:
        doc = _last_json_line(raw.get("tail"))
        note = "recovered from tail" if doc is not None \
            else "unparseable (crash or tail cut mid-line)"
    return {"name": name, "n": raw.get("n"), "rc": raw.get("rc"),
            "doc": doc, "note": note}


def extract_metrics(doc):
    """Flatten one bench line to ``{key: number}``. Top-level numerics
    keep their names; ``configs`` entries become ``configs.<name>``
    (compact-summary scalars directly, folded rich configs via their
    ``value``); dict-valued fields (bucket_histogram, fallback_causes)
    contribute their SUM as ``<key>.total`` so the trajectory shows
    volume drift without a column per bucket."""
    out = {}
    if not isinstance(doc, dict):
        return out
    for k, v in doc.items():
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[k] = v
        elif k == "configs" and isinstance(v, dict):
            for cname, c in v.items():
                if isinstance(c, bool):
                    continue
                if isinstance(c, (int, float)):
                    out[f"configs.{cname}"] = c
                elif isinstance(c, dict):
                    cv = c.get("value")
                    if isinstance(cv, (int, float)) \
                            and not isinstance(cv, bool):
                        out[f"configs.{cname}"] = cv
        elif isinstance(v, dict):
            nums = [x for x in v.values()
                    if isinstance(x, (int, float))
                    and not isinstance(x, bool)]
            if nums:
                out[f"{k}.total"] = sum(nums)
    return out


def diff_rounds(rounds):
    """Aligned trajectory over loaded rounds → report dict. Every
    metric seen in ANY round gets a row; rounds where it is absent
    (older schema, crashed round) show ``"n/a"`` — tolerance to
    missing fields is the point, not an error path."""
    per_round = [extract_metrics(r["doc"]) for r in rounds]
    keys = sorted({k for m in per_round for k in m})
    rows = []
    for k in keys:
        vals = [m.get(k) for m in per_round]
        present = [(i, v) for i, v in enumerate(vals) if v is not None]
        row = {
            "metric": k,
            "values": [NA if v is None else v for v in vals],
            "first": NA, "last": NA, "delta": NA, "pct": NA,
            "trend": NA,
        }
        if len(present) >= 2:
            (_, first), (_, last) = present[0], present[-1]
            delta = round(last - first, 4)
            pct = round((last - first) / abs(first) * 100, 2) \
                if first else None
            pol = polarity(k)
            trend = "~"
            if pct is not None and pol != 0 \
                    and abs(pct) >= REGRESSION_THRESHOLD_PCT:
                worse = (pct < 0) if pol > 0 else (pct > 0)
                trend = "REGRESSION" if worse else "improved"
            row.update(first=first, last=last, delta=delta,
                       pct=NA if pct is None else pct, trend=trend)
        elif len(present) == 1:
            row.update(first=present[0][1], last=present[0][1])
        rows.append(row)
    headers = []
    for r, m in zip(rounds, per_round):
        doc = r["doc"] or {}
        headers.append({
            "name": r["name"], "n": r["n"], "rc": r["rc"],
            "note": r["note"],
            # provenance header (bench.py stamps these since
            # schema_rev 2); absent in older rounds → explicit n/a
            "schema_rev": doc.get("schema_rev", NA),
            "git_rev": doc.get("git_rev", NA),
            "metric": doc.get("metric", NA),
            "value": doc.get("value", NA),
            "n_metrics": len(m),
        })
    regressions = [r["metric"] for r in rows if r["trend"] == "REGRESSION"]
    return {"rounds": headers, "metrics": rows,
            "regressions": regressions,
            "schema_revs": sorted({h["schema_rev"] for h in headers},
                                  key=str)}


def format_report(report):
    """The human-facing text report: one header line per round, then
    the aligned metric table, regressions summarised last."""
    lines = []
    hs = report["rounds"]
    lines.append(f"bench trajectory: {len(hs)} rounds")
    for h in hs:
        lines.append(
            f"  {h['name']}: rc={h['rc']} schema_rev={h['schema_rev']} "
            f"git_rev={h['git_rev']} metric={h['metric']} "
            f"value={h['value']} [{h['note']}]"
        )
    if len(report["schema_revs"]) > 1:
        lines.append(
            f"  NOTE: mixed schema_revs {report['schema_revs']} — "
            "renamed fields may align as n/a, not as each other"
        )
    w = max((len(r["metric"]) for r in report["metrics"]), default=10)
    lines.append("")
    lines.append(
        f"  {'metric'.ljust(w)}  {'first':>12}  {'last':>12}  "
        f"{'delta':>12}  {'pct':>8}  trend"
    )
    for r in report["metrics"]:
        lines.append(
            f"  {r['metric'].ljust(w)}  {str(r['first']):>12}  "
            f"{str(r['last']):>12}  {str(r['delta']):>12}  "
            f"{str(r['pct']):>8}  {r['trend']}"
        )
    lines.append("")
    if report["regressions"]:
        lines.append(
            f"REGRESSIONS ({len(report['regressions'])}): "
            + ", ".join(report["regressions"])
        )
    else:
        lines.append("no regressions flagged")
    return "\n".join(lines)


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m foundationdb_tpu.tools.benchdiff",
        description="Diff bench metrics across BENCH_r* round files.",
    )
    ap.add_argument("files", nargs="+", help="round files, in order")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the report as JSON instead of text")
    args = ap.parse_args(argv)
    rounds = [load_round(p) for p in args.files]
    report = diff_rounds(rounds)
    if args.as_json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(format_report(report))
    # nonzero exit when the trajectory regressed: the same gate shape
    # as the smoke modes, so CI can chain `bench && benchdiff`
    return 1 if report["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
