"""Fault-coverage reporter: fired runtime sites vs the static table.

flowlint FL011 enumerates every coded-error fabrication site in the
tree into ``analysis/faultsites.txt``; the runtime witness
(``utils/faultcov.py``) counts which of those sites actually fire.
This tool closes the loop — the reference's question "did the chaos
campaign reach this error path?" becomes a diff between two sets:

* **never-fired** sites — enumerated statically, not driven by the
  run. Coverage debt, reported but not fatal (a single run cannot
  reach everything).
* **violations** — fired sites absent from the static table. These
  fail the run (exit 1): either FL011's enumeration has a hole or a
  fabrication site dodged the lint, and both are bugs. Matching is
  wildcard-aware: a fired ``module:qualname:code`` is covered by a
  ``module:qualname:*`` entry (dynamic-name sites can fabricate any
  code).

Input is a witness snapshot — the canonical ``witness_doc()`` JSON —
from ``--snapshot FILE``, or produced in-process by ``--probe``, which
runs the canonical seeded chaos simulation (buggify + crashes +
machine kills over conflicting cycle/counter workloads). The probe is
deterministic: the same ``--seed`` yields byte-identical snapshots,
and ``tests/test_flowlint_v3.py`` pins that contract plus the
fired ⊆ enumerated subset property.

Usage::

    python -m foundationdb_tpu.tools.faultcov --probe
    python -m foundationdb_tpu.tools.faultcov --probe --seed 7 --json
    python -m foundationdb_tpu.tools.faultcov --snapshot witness.json
"""

import argparse
import json
import os
import sys
import tempfile

DEFAULT_PROBE_SEED = 11


def _table_path():
    import foundationdb_tpu

    pkg = os.path.dirname(os.path.abspath(foundationdb_tpu.__file__))
    return os.path.join(pkg, "analysis", "faultsites.txt")


def load_table(path=None):
    """``{site_id: table_line}`` from faultsites.txt (FL011's format)."""
    from foundationdb_tpu.analysis.rules.fl011_faultsites import (
        load_faultsites,
    )

    path = path or _table_path()
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        return load_faultsites(f.read())


def site_covered(site, table):
    """Wildcard-aware membership: an exact entry, or the site's
    ``module:qualname:*`` dynamic entry."""
    if site in table:
        return True
    return site.rsplit(":", 1)[0] + ":*" in table


def coverage_report(fired_counts, table):
    """The diff both the CLI and the bench gauges read:

    ``sites_total``/``sites_fired``/``coverage_pct`` count STATIC
    table entries (a wildcard entry counts fired when any of its codes
    fired); ``never_fired`` lists unreached entries; ``violations``
    lists fired sites the table does not cover."""
    fired = set(fired_counts)
    hit = set()
    for site in fired:
        if site in table:
            hit.add(site)
        else:
            wild = site.rsplit(":", 1)[0] + ":*"
            if wild in table:
                hit.add(wild)
    total = len(table)
    return {
        "sites_total": total,
        "sites_fired": len(hit),
        "coverage_pct": round(100.0 * len(hit) / total, 2) if total
        else 0.0,
        "never_fired": sorted(set(table) - hit),
        "violations": sorted(s for s in fired
                             if not site_covered(s, table)),
        "fired_counts": {s: fired_counts[s] for s in sorted(fired)},
    }


def _version_skew_reader(cluster, n_ops):
    """Clients racing the MVCC window from both ends — what the RPC
    deployment's storageworker wait/fence path produces against a
    lagging or trimmed replica: a read version ahead of storage
    (1009 future_version) and one held past the oldest retained
    version (1007 transaction_too_old). Both retryable; the probe
    bounds them instead of retrying."""
    from foundationdb_tpu.core.errors import FDBError

    router = cluster.storage
    for _ in range(n_ops):
        yield
        for skew_version in (router.version + 50, -1):
            try:
                router.get(b"cycle/skew-probe", skew_version)
            except FDBError as e:
                if e.code not in (1007, 1009, 1037):
                    raise


def run_probe(seed=DEFAULT_PROBE_SEED, datadir=None, steps_budget=None):
    """The canonical chaos probe: a seeded simulation under the full
    fault battery, faultcov armed, returning the canonical witness
    snapshot (JSON text). Deterministic per seed — same seed, byte-
    identical snapshot.

    The fault surface is chosen to reach every client-visible chaos
    code: buggified commit/GRV proxies (1021, 1037), conflicting
    cycle workloads (1020 not_committed), crash/recovery plus machine
    kills (1007 transaction_too_old, 1009 future_version via storage
    fencing and lag)."""
    import random

    from foundationdb_tpu.sim.simulation import Simulation
    from foundationdb_tpu.sim.workloads import (
        counter_workload,
        cycle_setup,
        cycle_workload,
        slow_cycle_workload,
    )
    from foundationdb_tpu.utils import faultcov

    owns_dir = datadir is None
    if owns_dir:
        datadir = tempfile.mkdtemp(prefix="fdbtpu-faultcov-")
    faultcov.reset()
    faultcov.enable()
    try:
        sim = Simulation(seed=seed, buggify=True, crash_p=0.01,
                         machines=4, datadir=datadir)
        # force-activate the client-path fault sites (activation is
        # otherwise a 25% coin per seed — the probe must certainly
        # reach 1021 and 1037; same idiom as the idempotency sims)
        sim.buggify._sites["commit_dropped"] = True
        sim.buggify._sites["commit_applied_then_unknown"] = True
        sim.buggify._sites["grv_rejected"] = True
        with sim:
            n_nodes = 12
            cycle_setup(sim.db, n_nodes)
            stats = {"committed": 0, "retried_1021": 0}
            for a in range(3):
                rng = random.Random(seed * 1000 + a)
                sim.add_workload(
                    f"cycle{a}",
                    cycle_workload(sim.db, n_nodes, 25, rng))
                sim.add_workload(
                    f"slow{a}",
                    slow_cycle_workload(sim.db, n_nodes, 12, rng))
            sim.add_workload(
                "ctr", counter_workload(sim.db, 30, stats))
            sim.add_workload(
                "skew", _version_skew_reader(sim.cluster, 10))
            sim.run(max_steps=steps_budget or 1_000_000)
            sim.quiesce()
        return faultcov.witness_doc()
    finally:
        faultcov.disable()
        faultcov.reset()


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m foundationdb_tpu.tools.faultcov",
        description="diff runtime-fired fault sites against the "
                    "static FL011 enumeration (analysis/faultsites"
                    ".txt); exit 1 on fired-but-unenumerated sites",
    )
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--snapshot", metavar="FILE",
                     help="witness_doc() JSON to analyze ('-' = stdin)")
    src.add_argument("--probe", action="store_true",
                     help="run the canonical seeded chaos simulation "
                          "to produce the snapshot in-process")
    ap.add_argument("--seed", type=int, default=DEFAULT_PROBE_SEED,
                    help="probe simulation seed (default: "
                         f"{DEFAULT_PROBE_SEED})")
    ap.add_argument("--table", default=None,
                    help="faultsites.txt override (default: the "
                         "installed package's)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    if args.probe:
        doc = run_probe(seed=args.seed)
    elif args.snapshot == "-":
        doc = sys.stdin.read()
    else:
        with open(args.snapshot, encoding="utf-8") as f:
            doc = f.read()
    fired_counts = json.loads(doc).get("fired", {})
    table = load_table(args.table)
    rep = coverage_report(fired_counts, table)

    if args.json:
        print(json.dumps(rep, indent=2, sort_keys=True))
    else:
        print(f"fault coverage: {rep['sites_fired']}/"
              f"{rep['sites_total']} enumerated sites fired "
              f"({rep['coverage_pct']}%)")
        for site in rep["never_fired"]:
            print(f"  never fired: {site}")
        for site in rep["violations"]:
            print(f"  VIOLATION — fired but not enumerated: {site}")
    return 1 if rep["violations"] else 0


if __name__ == "__main__":
    sys.exit(main())
