"""Backup and restore: range snapshots plus a continuous mutation log.

Ref parity: fdbclient/BackupAgent.actor.cpp + fdbbackup — the reference
backs a database up as (a) key-range snapshot files cut at some
version, and (b) a log of every mutation committed after the snapshot
began, so restore = load snapshot + replay log to a target version
(point-in-time restore). Ours keeps that exact two-stream layout in a
backup directory:

    backup-dir/
      snapshot-<version>.jsonl   one {"k","v"} per line (latin-1 escaped)
      log.jsonl                  one {"v", "muts"} per committed version
      restorable.json            manifest: snapshot version + log range

The mutation log is fed from the TLog (the reference's backup workers
pull from the same place), via ``BackupAgent.pull_log()`` — simulation
or an operator loop pumps it.
"""

import json
import os

from foundationdb_tpu.core.errors import FDBError
from foundationdb_tpu.core.mutations import Mutation, Op


def _enc(b):
    return b.decode("latin-1")


def _dec(s):
    return s.encode("latin-1")


def _scan_snapshot_to_file(tr, path, chunk):
    """Paginated consistent range dump at ``tr``'s read version (the
    one snapshot scan both agents share)."""
    with open(path, "w") as f:
        begin = b""
        while True:
            rows = tr.get_range(begin, b"\xff", limit=chunk, snapshot=True)
            for k, val in rows:
                f.write(json.dumps({"k": _enc(k), "v": _enc(val)}) + "\n")
            if len(rows) < chunk:
                break
            begin = rows[-1][0] + b"\x00"


def _atomic_json_write(path, obj):
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


class BackupAgent:
    """Drives one backup of a database into ``backup_dir``.

    Ref: BackupAgent submitBackup / the backup worker loop.
    """

    def __init__(self, db, backup_dir):
        self.db = db
        self.dir = backup_dir
        os.makedirs(backup_dir, exist_ok=True)
        self.snapshot_version = None
        self._log_path = os.path.join(backup_dir, "log.jsonl")
        self._log_from = None  # first version the log covers
        self._log_through = None  # last version pulled

    # ── snapshot (ref: the backup snapshot's getRange dump) ──
    def snapshot(self, chunk=1000):
        """Cut a consistent range snapshot at one read version."""
        tr = self.db.create_transaction()
        v = tr.get_read_version()
        # pin the tlog BEFORE the scan: commits interleaved during the
        # scan (a yielding snapshot, other actors) plus a durability pump
        # must not pop records in (v, durable] before the hold exists —
        # those versions belong to the backup log
        self.db._cluster.tlog.hold_pop(f"backup@{id(self)}", v)
        path = os.path.join(self.dir, f"snapshot-{v}.jsonl")
        try:
            _scan_snapshot_to_file(tr, path, chunk)
        except BaseException:
            # a failed scan (TOO_OLD on a huge keyspace, IO error) must not
            # leave the tlog pinned at v forever
            self.db._cluster.tlog.release_pop(f"backup@{id(self)}")
            raise
        self.snapshot_version = v
        # the log covers (snapshot_version, target], anchored at v
        self._log_from = v
        self._log_through = v
        self._write_manifest()
        return v

    def stop(self):
        """Release the tlog pin (backup discontinued or complete)."""
        self.db._cluster.tlog.release_pop(f"backup@{id(self)}")

    # ── continuous log (ref: backup workers popping the tlog) ──
    def pull_log(self):
        """Append all tlog records newer than what we've pulled."""
        if self._log_from is None:
            raise RuntimeError("snapshot() first: the log anchors to it")
        tlog = self.db._cluster.tlog
        with open(self._log_path, "a") as f:
            for version, muts in tlog.peek(self._log_through):
                if version <= self._log_through:
                    continue
                f.write(
                    json.dumps(
                        {
                            "v": version,
                            "muts": [
                                [m.op.value, _enc(m.key),
                                 _enc(m.param) if m.param is not None else None]
                                for m in muts
                            ],
                        }
                    )
                    + "\n"
                )
                self._log_through = version
        tlog.hold_pop(f"backup@{id(self)}", self._log_through)
        self._write_manifest()
        return self._log_through

    def _write_manifest(self):
        _atomic_json_write(os.path.join(self.dir, "restorable.json"), {
            "snapshot_version": self.snapshot_version,
            "log_from": self._log_from,
            "log_through": self._log_through,
        })


BACKUP_STATE_PREFIX = b"\xff/backup/"


class ContinuousBackupAgent:
    """A continuously running incremental backup (ref:
    fdbclient/FileBackupAgent.actor.cpp + BackupAgentBase: the agent
    persists its progress in the system keyspace, writes incremental
    mutation-log files forever, and any version within retention is
    restorable).

    Shape here:
    - ``start()`` registers a change feed over the user keyspace, cuts
      the base snapshot, and persists agent state under
      ``\\xff/backup/<name>/`` through ordinary transactions (tlog-
      durable, recovered like user data). No tlog pin: the FEED buffers
      post-registration mutations, which is the reference's
      backup-worker position in the pipeline.
    - ``tick()`` (pumped by an operator loop or the simulation) drains
      the feed into a ``log-<from>-<to>.jsonl`` chunk file, pops the
      feed (the consumer checkpoint), and advances the persisted
      ``log_through`` — restore can then target ANY version in
      [snapshot_version, log_through].
    - a trimmed feed (1007: the agent fell behind retention) or a feed
      lost to cluster recovery re-bases loudly: new feed + new
      snapshot, continuity restarts (ref: the agent re-snapshotting
      when it cannot guarantee log continuity).
    - ``resume(db, name)`` reopens a running agent from its persisted
      system-keyspace state after an agent-process crash.
    """

    FEED_RANGE = (b"", b"\xff")

    def __init__(self, db, backup_dir, name="default"):
        self.db = db
        self.dir = backup_dir
        self.name = name
        self.feed_id = f"backup/{name}"
        self.snapshot_version = None
        self.log_through = None
        self.chunks = []  # [(from_v, to_v, filename)]
        self.rebased = 0  # times continuity restarted (trim/recovery)
        os.makedirs(backup_dir, exist_ok=True)

    # ── system-keyspace state (ref: the backup config keyspace) ──
    def _state_key(self, field):
        return BACKUP_STATE_PREFIX + self.name.encode() + b"/" + field

    def _persist(self, **fields):
        def _apply(tr):
            for k, v in fields.items():
                tr.set(self._state_key(k.encode()), str(v).encode())

        self.db.run(_apply)

    @classmethod
    def load_state(cls, db, name="default"):
        """The persisted agent state (None when no agent ever ran)."""
        prefix = BACKUP_STATE_PREFIX + name.encode() + b"/"

        def _read(tr):
            return {
                k[len(prefix):].decode(): v.decode()
                for k, v in tr.get_range(prefix, prefix + b"\xff")
            }

        state = db.run(_read)
        return state or None

    @classmethod
    def resume(cls, db, backup_dir, name="default"):
        """Reopen from persisted state (agent-process restart)."""
        state = cls.load_state(db, name)
        if state is None or state.get("state") != "running":
            raise RuntimeError(f"no running backup agent {name!r}")
        agent = cls(db, backup_dir, name)
        agent.snapshot_version = int(state["snapshot_version"])
        agent.log_through = int(state["log_through"])
        m = describe_backup(backup_dir)
        agent.chunks = [tuple(c) for c in m.get("chunks", [])]
        return agent

    # ── lifecycle ──
    def start(self):
        feeds = self.db._cluster.change_feeds
        try:
            feeds.register(self.feed_id, *self.FEED_RANGE)
        except FDBError:
            # stale feed from a prior agent incarnation: restart it so
            # the pop frontier cannot hide pre-snapshot history
            feeds.deregister(self.feed_id)
            feeds.register(self.feed_id, *self.FEED_RANGE)
        try:
            v = self._cut_snapshot()
        except BaseException:
            # a failed snapshot must not leave a FRESH feed paired with
            # stale persisted state: a retried tick() would read the new
            # feed from the old cursor without error and silently skip
            # everything between the trim and this registration
            feeds.deregister(self.feed_id)
            raise
        self.snapshot_version = v
        self.log_through = v
        self.chunks = []
        self._persist(state="running", snapshot_version=v, log_through=v)
        self._write_manifest()
        return v

    def _cut_snapshot(self, chunk=1000):
        tr = self.db.create_transaction()
        v = tr.get_read_version()
        path = os.path.join(self.dir, f"snapshot-{v}.jsonl")
        _scan_snapshot_to_file(tr, path, chunk)
        return v

    def tick(self):
        """One agent round: drain the feed → an incremental chunk file,
        checkpoint, persist progress. Returns log_through."""
        feeds = self.db._cluster.change_feeds
        try:
            entries = feeds.read(self.feed_id, self.log_through)
        except FDBError as e:
            # 1007: trimmed past our checkpoint (agent fell behind) —
            # continuity is broken, re-base. 2000: the feed died with a
            # cluster recovery — same treatment.
            from foundationdb_tpu.utils.trace import TraceEvent

            TraceEvent("BackupAgentRebase", severity=30).detail(
                name=self.name, error=e.code).log()
            self.rebased += 1
            self.start()
            return self.log_through
        if not entries:
            return self.log_through
        first, last = entries[0][0], entries[-1][0]
        fname = f"log-{first}-{last}.jsonl"
        with open(os.path.join(self.dir, fname), "w") as f:
            for version, muts in entries:
                f.write(json.dumps({
                    "v": version,
                    "muts": [
                        [m.op.value, _enc(m.key),
                         _enc(m.param) if m.param is not None else None]
                        for m in muts
                    ],
                }) + "\n")
        # Crash-ordering: manifest + persisted cursor FIRST, feed pop
        # LAST (the reference pops only after the consumer checkpoint is
        # durable). A crash between the manifest and the cursor persist
        # resumes with an older cursor and re-chunks entries the
        # manifest already references — restore() dedupes by version,
        # so overlap is safe; popping first would instead 1007 the
        # resumed agent into a spurious full re-base.
        if (first, last, fname) not in self.chunks:
            self.chunks.append((first, last, fname))
        self.log_through = last
        self._write_manifest()
        self._persist(log_through=last)
        feeds.pop(self.feed_id, last)
        return last

    def stop(self):
        try:
            self.db._cluster.change_feeds.deregister(self.feed_id)
        except FDBError:
            pass
        self._persist(state="stopped")

    def _write_manifest(self):
        _atomic_json_write(os.path.join(self.dir, "restorable.json"), {
            "snapshot_version": self.snapshot_version,
            "log_from": self.snapshot_version,
            "log_through": self.log_through,
            "chunks": self.chunks,
            "continuous": True,
        })


def describe_backup(backup_dir):
    """The backup's manifest (ref: fdbbackup describe)."""
    with open(os.path.join(backup_dir, "restorable.json")) as f:
        return json.load(f)


def restore(db, backup_dir, target_version=None, prefix=b"", ranges=None):
    """Restore a backup into ``db`` (ref: fdbrestore / performRestore).

    Loads the snapshot, then replays logged mutations with version ≤
    ``target_version`` (default: everything), all through normal
    transactions so the restored data is itself durable/replicated.
    ``ranges``: restrict the restore to these [begin, end) key ranges
    (ref: fdbrestore's -k range restore) — snapshot rows outside them
    are skipped and logged mutations are clipped. Returns the version
    the restore reached.
    """
    manifest = describe_backup(backup_dir)
    sv = manifest["snapshot_version"]
    if target_version is None:
        target_version = manifest["log_through"]
    if target_version < sv:
        raise ValueError(
            f"target_version {target_version} predates snapshot {sv}"
        )

    def in_ranges(key):
        return ranges is None or any(b <= key < e for b, e in ranges)

    snap_path = os.path.join(backup_dir, f"snapshot-{sv}.jsonl")
    batch = []

    def flush(rows):
        def _apply(tr):
            for k, v in rows:
                tr.set(prefix + k, v)

        db.run(_apply)

    with open(snap_path) as f:
        for line in f:
            row = json.loads(line)
            key = _dec(row["k"])
            if not in_ranges(key):
                continue
            batch.append((key, _dec(row["v"])))
            if len(batch) >= 500:
                flush(batch)
                batch = []
    if batch:
        flush(batch)

    # mutation-log sources: the continuous agent's chunk files (in
    # order), or the legacy single log.jsonl
    log_paths = [
        os.path.join(backup_dir, fname)
        for _, _, fname in sorted(manifest.get("chunks", []))
    ]
    legacy = os.path.join(backup_dir, "log.jsonl")
    if os.path.exists(legacy):
        log_paths.append(legacy)
    replayed_through = sv  # versions ≤ this are already applied: chunks
    # may overlap after a crash between chunk write and feed pop, and
    # atomic ops must replay each version exactly once
    for log_path in log_paths:
        with open(log_path) as f:
            for line in f:
                rec = json.loads(line)
                if rec["v"] <= replayed_through or rec["v"] > target_version:
                    continue
                replayed_through = rec["v"]
                muts = []
                for op, k, p in rec["muts"]:
                    op = Op(op)
                    key = _dec(k)
                    param = _dec(p) if p is not None else None
                    if op == Op.CLEAR_RANGE and param is not None:
                        if ranges is not None:
                            # clip the clear to each restored range
                            for rb, re_ in ranges:
                                cb, ce = max(key, rb), min(param, re_)
                                if cb < ce:
                                    muts.append(Mutation(
                                        op, prefix + cb, prefix + ce
                                    ))
                            continue
                        param = prefix + param  # the param is the end KEY
                    elif not in_ranges(key):
                        continue
                    muts.append(Mutation(op, prefix + key, param))
                _replay(db, muts)
    return target_version


def _replay(db, muts):
    def _apply(tr):
        for m in muts:
            if m.op == Op.SET:
                tr.set(m.key, m.param)
            elif m.op == Op.CLEAR_RANGE:
                tr.clear_range(m.key, m.param)
            elif m.op == Op.CLEAR:
                tr.clear(m.key)
            elif m.op in (Op.SET_VERSIONSTAMPED_KEY, Op.SET_VERSIONSTAMPED_VALUE):
                # the tlog holds these already substituted by the proxy
                tr.set(m.key, m.param)
            else:  # atomic ops re-apply as atomics (replay is idempotent
                # per-version because restore replays each version once)
                tr._atomic(m.op, m.key, m.param)

    db.run(_apply)
