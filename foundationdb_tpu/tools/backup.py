"""Backup and restore: range snapshots plus a continuous mutation log.

Ref parity: fdbclient/BackupAgent.actor.cpp + fdbbackup — the reference
backs a database up as (a) key-range snapshot files cut at some
version, and (b) a log of every mutation committed after the snapshot
began, so restore = load snapshot + replay log to a target version
(point-in-time restore). Ours keeps that exact two-stream layout in a
backup directory:

    backup-dir/
      snapshot-<version>.jsonl   one {"k","v"} per line (latin-1 escaped)
      log.jsonl                  one {"v", "muts"} per committed version
      restorable.json            manifest: snapshot version + log range

The mutation log is fed from the TLog (the reference's backup workers
pull from the same place), via ``BackupAgent.pull_log()`` — simulation
or an operator loop pumps it.
"""

import json
import os

from foundationdb_tpu.core.mutations import Mutation, Op


def _enc(b):
    return b.decode("latin-1")


def _dec(s):
    return s.encode("latin-1")


class BackupAgent:
    """Drives one backup of a database into ``backup_dir``.

    Ref: BackupAgent submitBackup / the backup worker loop.
    """

    def __init__(self, db, backup_dir):
        self.db = db
        self.dir = backup_dir
        os.makedirs(backup_dir, exist_ok=True)
        self.snapshot_version = None
        self._log_path = os.path.join(backup_dir, "log.jsonl")
        self._log_from = None  # first version the log covers
        self._log_through = None  # last version pulled

    # ── snapshot (ref: the backup snapshot's getRange dump) ──
    def snapshot(self, chunk=1000):
        """Cut a consistent range snapshot at one read version."""
        tr = self.db.create_transaction()
        v = tr.get_read_version()
        # pin the tlog BEFORE the scan: commits interleaved during the
        # scan (a yielding snapshot, other actors) plus a durability pump
        # must not pop records in (v, durable] before the hold exists —
        # those versions belong to the backup log
        self.db._cluster.tlog.hold_pop(f"backup@{id(self)}", v)
        path = os.path.join(self.dir, f"snapshot-{v}.jsonl")
        try:
            with open(path, "w") as f:
                begin = b""
                while True:
                    rows = tr.get_range(begin, b"\xff", limit=chunk, snapshot=True)
                    for k, val in rows:
                        f.write(json.dumps({"k": _enc(k), "v": _enc(val)}) + "\n")
                    if len(rows) < chunk:
                        break
                    begin = rows[-1][0] + b"\x00"
        except BaseException:
            # a failed scan (TOO_OLD on a huge keyspace, IO error) must not
            # leave the tlog pinned at v forever
            self.db._cluster.tlog.release_pop(f"backup@{id(self)}")
            raise
        self.snapshot_version = v
        # the log covers (snapshot_version, target], anchored at v
        self._log_from = v
        self._log_through = v
        self._write_manifest()
        return v

    def stop(self):
        """Release the tlog pin (backup discontinued or complete)."""
        self.db._cluster.tlog.release_pop(f"backup@{id(self)}")

    # ── continuous log (ref: backup workers popping the tlog) ──
    def pull_log(self):
        """Append all tlog records newer than what we've pulled."""
        if self._log_from is None:
            raise RuntimeError("snapshot() first: the log anchors to it")
        tlog = self.db._cluster.tlog
        with open(self._log_path, "a") as f:
            for version, muts in tlog.peek(self._log_through):
                if version <= self._log_through:
                    continue
                f.write(
                    json.dumps(
                        {
                            "v": version,
                            "muts": [
                                [m.op.value, _enc(m.key),
                                 _enc(m.param) if m.param is not None else None]
                                for m in muts
                            ],
                        }
                    )
                    + "\n"
                )
                self._log_through = version
        tlog.hold_pop(f"backup@{id(self)}", self._log_through)
        self._write_manifest()
        return self._log_through

    def _write_manifest(self):
        manifest = {
            "snapshot_version": self.snapshot_version,
            "log_from": self._log_from,
            "log_through": self._log_through,
        }
        tmp = os.path.join(self.dir, "restorable.json.tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, os.path.join(self.dir, "restorable.json"))


def describe_backup(backup_dir):
    """The backup's manifest (ref: fdbbackup describe)."""
    with open(os.path.join(backup_dir, "restorable.json")) as f:
        return json.load(f)


def restore(db, backup_dir, target_version=None, prefix=b""):
    """Restore a backup into ``db`` (ref: fdbrestore / performRestore).

    Loads the snapshot, then replays logged mutations with version ≤
    ``target_version`` (default: everything), all through normal
    transactions so the restored data is itself durable/replicated.
    Returns the version the restore reached.
    """
    manifest = describe_backup(backup_dir)
    sv = manifest["snapshot_version"]
    if target_version is None:
        target_version = manifest["log_through"]
    if target_version < sv:
        raise ValueError(
            f"target_version {target_version} predates snapshot {sv}"
        )

    snap_path = os.path.join(backup_dir, f"snapshot-{sv}.jsonl")
    batch = []

    def flush(rows):
        def _apply(tr):
            for k, v in rows:
                tr.set(prefix + k, v)

        db.run(_apply)

    with open(snap_path) as f:
        for line in f:
            row = json.loads(line)
            batch.append((_dec(row["k"]), _dec(row["v"])))
            if len(batch) >= 500:
                flush(batch)
                batch = []
    if batch:
        flush(batch)

    log_path = os.path.join(backup_dir, "log.jsonl")
    if os.path.exists(log_path):
        with open(log_path) as f:
            for line in f:
                rec = json.loads(line)
                if rec["v"] <= sv or rec["v"] > target_version:
                    continue
                muts = []
                for op, k, p in rec["muts"]:
                    op = Op(op)
                    param = _dec(p) if p is not None else None
                    if op == Op.CLEAR_RANGE and param is not None:
                        param = prefix + param  # the param is the end KEY
                    muts.append(Mutation(op, prefix + _dec(k), param))
                _replay(db, muts)
    return target_version


def _replay(db, muts):
    def _apply(tr):
        for m in muts:
            if m.op == Op.SET:
                tr.set(m.key, m.param)
            elif m.op == Op.CLEAR_RANGE:
                tr.clear_range(m.key, m.param)
            elif m.op == Op.CLEAR:
                tr.clear(m.key)
            elif m.op in (Op.SET_VERSIONSTAMPED_KEY, Op.SET_VERSIONSTAMPED_VALUE):
                # the tlog holds these already substituted by the proxy
                tr.set(m.key, m.param)
            else:  # atomic ops re-apply as atomics (replay is idempotent
                # per-version because restore replays each version once)
                tr._atomic(m.op, m.key, m.param)

    db.run(_apply)
