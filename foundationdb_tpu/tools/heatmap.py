r"""Split-point advice from the workload heatmaps.

Ref parity: the DD split-point machinery around
fdbserver/StorageMetrics.actor.cpp — the reference picks shard split
keys at byte-sample quantiles so each side carries equal load. Here the
input is the cluster's workload-attribution document (the
``\xff\xff/metrics/hot_ranges`` special key / ``metrics hot`` RPC /
``cluster.workload.hot_ranges`` in status): decayed hot-range
histograms per dimension (conflict / read / write). Advice = the keys
where CUMULATIVE heat crosses the i/n quantiles, i.e. split points
that would spread the observed heat evenly across n shards.

Usage::

    from foundationdb_tpu.tools import heatmap as hm
    hm.split_advice(cluster.hot_ranges_status(), n=4, dim="read")

or, against a served cluster::

    python -m foundationdb_tpu.tools.heatmap --cluster-file fdb.cluster \
        --dim conflict -n 4

(with ``--json -`` the document is read from stdin instead — pipe a
saved ``\xff\xff/metrics/hot_ranges`` value in).
"""

import json
import sys


def split_points_from_rows(rows, n):
    """Split keys (str) at cumulative-heat quantiles over snapshot
    ``rows`` ([{begin, end, heat}, ...], sorted by begin — the exact
    shape KeyRangeHeatmap.snapshot() emits). Returns at most n-1 keys;
    consecutive duplicates (one range hot enough to span several
    quantiles) are collapsed, matching KeyRangeHeatmap.split_points."""
    if n <= 1 or not rows:
        return []
    total = sum(r["heat"] for r in rows)
    if total <= 0:
        return []
    # the exact algorithm KeyRangeHeatmap.split_points runs over its
    # anchors: cut at the first range whose START sits at-or-past each
    # i/n cumulative-heat quantile (so the first range's begin — a
    # no-op split — is never advised)
    points = []
    acc = 0.0
    targets = [total * q / n for q in range(1, n)]
    ti = 0
    for r in rows:
        while ti < len(targets) and acc >= targets[ti]:
            key = r["begin"]
            if not points or points[-1] != key:
                points.append(key)
            ti += 1
        acc += r["heat"]
    return points


def shard_heat_at(rows, points):
    """Heat per advised shard: snapshot ``rows`` partitioned at the
    ``points`` split keys (a row belongs to the shard its begin key
    falls in)."""
    shards = []
    acc = 0.0
    pi = 0
    for r in rows:
        while pi < len(points) and r["begin"] >= points[pi]:
            shards.append(round(acc, 4))
            acc = 0.0
            pi += 1
        acc += r["heat"]
    shards.append(round(acc, 4))
    while pi < len(points):  # trailing empty shards (dup-collapsed tail)
        shards.append(0.0)
        pi += 1
    return shards


def split_advice(doc, n=4, dim="read"):
    """Advice record for one dimension of a workload-attribution
    document: the suggested split keys plus the heat each resulting
    shard would carry (so an operator can see HOW uneven the current
    layout is versus the advised one)."""
    rows = (doc.get("hot_ranges") or {}).get(dim) or []
    points = split_points_from_rows(rows, n)
    return {
        "dim": dim,
        "n": n,
        "total_heat": round(sum(r["heat"] for r in rows), 4),
        "split_points": points,
        "shard_heat": shard_heat_at(rows, points),
    }


def heat_trend(history_doc, n=4, dim="read"):
    """Per-advised-shard heat TRAJECTORY from the metrics-history
    document (utils/timeseries.py): split points advised from the
    NEWEST window's hot ranges, then every retained window's rows
    partitioned at those same boundaries — so an operator sees whether
    the advised split would have balanced the load over time or only
    balances this instant's spike."""
    windows = ((history_doc or {}).get("heat") or {}).get(dim) or []
    if not windows:
        return {"dim": dim, "n": n, "split_points": [], "windows": []}
    # heat windows retain the top-K rows by heat; both the quantile
    # walk and the partition need begin-key order
    points = split_points_from_rows(
        sorted(windows[-1]["rows"], key=lambda r: r["begin"]), n)
    return {
        "dim": dim,
        "n": n,
        "split_points": points,
        "windows": [
            {"t": w["t"], "total_heat": round(w["total"], 4),
             "shard_heat": shard_heat_at(
                 sorted(w["rows"], key=lambda r: r["begin"]), points)}
            for w in windows
        ],
    }


def _fetch_doc(ns):
    if ns.json == "-":
        return json.load(sys.stdin)
    if ns.json:
        with open(ns.json) as f:
            return json.load(f)
    from foundationdb_tpu.rpc.service import RemoteCluster

    rc = RemoteCluster.from_cluster_file(ns.cluster_file)
    try:
        # --trend consumes the history document (heat per window);
        # the instant advice consumes the hot_ranges document
        if ns.trend:
            return rc.history_status()
        return rc.hot_ranges_status()
    finally:
        rc.close()


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        prog="heatmap", description="hot-range split-point advice")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--cluster-file", help="cluster to poll")
    src.add_argument("--json", help="saved hot_ranges document "
                                    "(- = stdin; with --trend: a saved "
                                    "history document)")
    ap.add_argument("--dim", default="read",
                    choices=("conflict", "read", "write"))
    ap.add_argument("-n", type=int, default=4,
                    help="target shard count (n-1 split points)")
    ap.add_argument("--trend", action="store_true",
                    help="per-advised-shard heat trajectory from the "
                         "metrics history instead of instant advice")
    ns = ap.parse_args(argv)
    doc = _fetch_doc(ns)
    if ns.trend:
        out = heat_trend(doc, n=ns.n, dim=ns.dim)
    else:
        out = split_advice(doc, n=ns.n, dim=ns.dim)
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
