"""System keyspace layout: cluster metadata stored as ordinary keys.

Ref parity: fdbclient/SystemData.cpp — the reference persists its shard
map in the ``\\xff/keyServers/`` range (one row per shard boundary whose
value names the owning team) and configuration under ``\\xff/conf/``.
Storing the map IN the database is what lets recovery rebuild placement
instead of resetting to full replication: the rows ride the same tlog →
storage pipeline as user data.
"""

import json
import struct

KEY_SERVERS_PREFIX = b"\xff/keyServers/"
KEY_SERVERS_END = b"\xff/keyServers0"  # '0' = '/'+1
CONF_REPLICATION = b"\xff/conf/replication"
# Region configuration row (ref: the region blocks of
# fdbclient/DatabaseConfiguration.cpp persisted under \xff/conf/) —
# the canonical JSON of server/region.py's RegionConfig. Riding the
# ordinary tlog → storage pipeline means the region config is restored
# by WAL recovery exactly like the shard map above it.
CONF_REGIONS = b"\xff/conf/regions"
# Database lock uid (ref: fdbclient/SystemData.cpp databaseLockedKey) —
# persisted so the lock survives recovery and rides the DR seed/stream.
DB_LOCKED = b"\xff/dbLocked"

# Commit idempotency ids (ref: fdbclient/IdempotencyId.actor.cpp — the
# idempotencyIdKeys range): one row per recently committed idempotent
# transaction, id → commit version. Written atomically WITH the commit's
# mutations, so the row's presence at any later read version proves the
# commit applied; the proxy GCs rows older than the MVCC window.
IDMP_PREFIX = b"\xff\x02/idmp/"
IDMP_END = b"\xff\x02/idmp0"


def idmp_key(idempotency_id):
    return IDMP_PREFIX + idempotency_id


def pack_version(v):
    return struct.pack(">q", v)


def unpack_version(b):
    return struct.unpack(">q", b)[0]


def encode_shard_map(shard_map):
    """ShardMap → [(key, value)] rows: one row per shard, keyed by its
    begin boundary, value = the owning team (ids are stable across
    recovery because storages are recruited in engine order)."""
    rows = []
    for i, begin in enumerate(shard_map.boundaries):
        rows.append(
            (
                KEY_SERVERS_PREFIX + begin,
                json.dumps(
                    {"team": shard_map.teams[i], "size": shard_map.sizes[i]}
                ).encode(),
            )
        )
    return rows


def decode_shard_map(rows):
    """[(key, value)] rows → (boundaries, teams, sizes), or None when no
    rows were persisted (bootstrap)."""
    if not rows:
        return None
    boundaries, teams, sizes = [], [], []
    for k, v in rows:
        if not k.startswith(KEY_SERVERS_PREFIX):
            continue
        meta = json.loads(v.decode())
        boundaries.append(k[len(KEY_SERVERS_PREFIX):])
        teams.append([int(s) for s in meta["team"]])
        sizes.append(int(meta.get("size", 0)))
    if not boundaries or boundaries[0] != b"":
        return None  # torn/partial map: fall back to bootstrap placement
    return boundaries, teams, sizes
