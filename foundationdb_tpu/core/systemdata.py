"""System keyspace layout: cluster metadata stored as ordinary keys.

Ref parity: fdbclient/SystemData.cpp — the reference persists its shard
map in the ``\\xff/keyServers/`` range (one row per shard boundary whose
value names the owning team) and configuration under ``\\xff/conf/``.
Storing the map IN the database is what lets recovery rebuild placement
instead of resetting to full replication: the rows ride the same tlog →
storage pipeline as user data.
"""

import json

KEY_SERVERS_PREFIX = b"\xff/keyServers/"
KEY_SERVERS_END = b"\xff/keyServers0"  # '0' = '/'+1
CONF_REPLICATION = b"\xff/conf/replication"
# Database lock uid (ref: fdbclient/SystemData.cpp databaseLockedKey) —
# persisted so the lock survives recovery and rides the DR seed/stream.
DB_LOCKED = b"\xff/dbLocked"


def encode_shard_map(shard_map):
    """ShardMap → [(key, value)] rows: one row per shard, keyed by its
    begin boundary, value = the owning team (ids are stable across
    recovery because storages are recruited in engine order)."""
    rows = []
    for i, begin in enumerate(shard_map.boundaries):
        rows.append(
            (
                KEY_SERVERS_PREFIX + begin,
                json.dumps(
                    {"team": shard_map.teams[i], "size": shard_map.sizes[i]}
                ).encode(),
            )
        )
    return rows


def decode_shard_map(rows):
    """[(key, value)] rows → (boundaries, teams, sizes), or None when no
    rows were persisted (bootstrap)."""
    if not rows:
        return None
    boundaries, teams, sizes = [], [], []
    for k, v in rows:
        if not k.startswith(KEY_SERVERS_PREFIX):
            continue
        meta = json.loads(v.decode())
        boundaries.append(k[len(KEY_SERVERS_PREFIX):])
        teams.append([int(s) for s in meta["team"]])
        sizes.append(int(meta.get("size", 0)))
    if not boundaries or boundaries[0] != b"":
        return None  # torn/partial map: fall back to bootstrap placement
    return boundaries, teams, sizes
