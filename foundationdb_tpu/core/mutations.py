"""Mutation model + atomic operations.

Ref parity: MutationRef in fdbclient/CommitTransaction.h and the atomic-op
implementations in flow/Arena.h / fdbclient/AtomicOps (doLittleEndianAdd,
doMin, doMax, doAnd, doOr, doXor, doByteMin, doByteMax, doAppendIfFits,
doCompareAndClear). Atomics evaluate server-side at apply time; the RYW
layer uses the same functions to show a transaction its own atomic writes.
"""

import enum
import struct


class Op(enum.Enum):
    SET = "set"
    CLEAR = "clear"  # single key
    CLEAR_RANGE = "clear_range"
    ADD = "add"
    BIT_AND = "bit_and"
    BIT_OR = "bit_or"
    BIT_XOR = "bit_xor"
    MIN = "min"
    MAX = "max"
    BYTE_MIN = "byte_min"
    BYTE_MAX = "byte_max"
    APPEND_IF_FITS = "append_if_fits"
    COMPARE_AND_CLEAR = "compare_and_clear"
    SET_VERSIONSTAMPED_KEY = "set_versionstamped_key"
    SET_VERSIONSTAMPED_VALUE = "set_versionstamped_value"


class Mutation:
    """One mutation: (op, key[, param]) or (CLEAR_RANGE, begin, end)."""

    __slots__ = ("op", "key", "param")

    def __init__(self, op, key, param=None):
        self.op = op
        # exact-type fast path: the hot constructors (txn.set, proxy id
        # rows) always pass bytes; bytes(bytes) still pays a call
        self.key = key if type(key) is bytes else bytes(key)
        self.param = (param if param is None or type(param) is bytes
                      else bytes(param))

    def __repr__(self):
        return f"Mutation({self.op.value}, {self.key!r}, {self.param!r})"


def _le_int(data, width):
    """Little-endian unsigned int of ``width`` bytes (zero-padded)."""
    padded = (data or b"")[:width].ljust(width, b"\x00")
    return int.from_bytes(padded, "little")


def apply_atomic(op, old, param):
    """New value for key given existing ``old`` (None = absent) and param.

    Widths follow FDB: the operand length defines the arithmetic width;
    existing values are truncated/zero-padded to it (ref: doLittleEndianAdd
    semantics). Returns None to mean "clear the key".
    """
    if op is Op.SET:
        return param
    if op is Op.CLEAR:
        return None
    width = len(param) if param is not None else 0
    if op is Op.ADD:
        if width == 0:
            return b""
        total = (_le_int(old, width) + _le_int(param, width)) % (1 << (8 * width))
        return total.to_bytes(width, "little")
    if op is Op.BIT_AND:
        if old is None:
            # ref: AND on absent key stores param (historical quirk kept
            # by fdbclient's doAndV2)
            return param
        return (_le_int(old, width) & _le_int(param, width)).to_bytes(width, "little")
    if op is Op.BIT_OR:
        return (_le_int(old, width) | _le_int(param, width)).to_bytes(width, "little")
    if op is Op.BIT_XOR:
        return (_le_int(old, width) ^ _le_int(param, width)).to_bytes(width, "little")
    if op is Op.MIN:
        if old is None:
            return param
        return min(_le_int(old, width), _le_int(param, width)).to_bytes(width, "little")
    if op is Op.MAX:
        if old is None:
            return param
        return max(_le_int(old, width), _le_int(param, width)).to_bytes(width, "little")
    if op is Op.BYTE_MIN:
        if old is None:
            return param
        return min(old, param)
    if op is Op.BYTE_MAX:
        if old is None:
            return param
        return max(old, param)
    if op is Op.APPEND_IF_FITS:
        from foundationdb_tpu.core.keys import MAX_VALUE_SIZE

        combined = (old or b"") + (param or b"")
        return combined if len(combined) <= MAX_VALUE_SIZE else (old or b"")
    if op is Op.COMPARE_AND_CLEAR:
        return None if old == param else old
    raise ValueError(f"not an atomic value op: {op}")


VERSIONSTAMP_PLACEHOLDER = b"\xff" * 10


def substitute_versionstamp(mutation, version, batch_order, txn_order):
    """Resolve SET_VERSIONSTAMPED_KEY/VALUE into a plain SET at commit.

    The final 4 bytes of key (VERSIONSTAMPED_KEY) or value
    (VERSIONSTAMPED_VALUE) are a little-endian offset of the 10-byte
    placeholder, per the v2 API-520+ format (ref: fdbclient/
    CommitTransaction.h transformVersionstampMutation).
    """
    from foundationdb_tpu.core.versions import Versionstamp

    stamp = Versionstamp.from_version(version, batch_order + txn_order).tr_version
    if mutation.op is Op.SET_VERSIONSTAMPED_KEY:
        data = mutation.key
        (off,) = struct.unpack("<I", data[-4:])
        if off + 10 > len(data) - 4:
            raise ValueError("versionstamp offset out of range")
        key = data[:off] + stamp + data[off + 10 : -4]
        return Mutation(Op.SET, key, mutation.param)
    if mutation.op is Op.SET_VERSIONSTAMPED_VALUE:
        data = mutation.param
        (off,) = struct.unpack("<I", data[-4:])
        if off + 10 > len(data) - 4:
            raise ValueError("versionstamp offset out of range")
        val = data[:off] + stamp + data[off + 10 : -4]
        return Mutation(Op.SET, mutation.key, val)
    return mutation


ATOMIC_OPS = {
    Op.ADD,
    Op.BIT_AND,
    Op.BIT_OR,
    Op.BIT_XOR,
    Op.MIN,
    Op.MAX,
    Op.BYTE_MIN,
    Op.BYTE_MAX,
    Op.APPEND_IF_FITS,
    Op.COMPARE_AND_CLEAR,
}
