"""Flat columnar conflict-range encoding — the commit hot path's wire
and packing format.

The legacy commit path re-parses every conflict range at every layer:
the client ships ``[(begin, end)]`` byte pairs, the proxy splits points
from ranges per transaction and builds ``TxnRequest`` objects, and the
packer walks those objects gathering keys before one batched limb
encode. At tens of thousands of commits/sec the per-transaction Python
churn — object construction, per-range slicing, per-txn list appends —
was the dominant commit-pipeline stage (``stage_pack_ms``).

The flat path encodes ONCE, client-side, into the exact bytes every
downstream layer consumes:

    entry(k)  = k padded to C=4*L bytes with \\x00  ||  >I(len(k))

which is precisely the resolver's limb encoding (core/keys.py KeyCodec):
``np.frombuffer(entry, '>u4')`` IS ``encode_lower(k)`` — the padded key
bytes are the big-endian limbs and the trailing word is the length limb.
For in-capacity keys ``encode_upper`` agrees with ``encode_lower``, so a
range packs as ``entry(begin) || entry(end)`` with no successor math.
A point key's end bound ``k+\\x00`` needs no entry of its own anywhere:
on the device path the point lanes store only the lower encoding, and on
the native path the padding byte AFTER the key inside its own entry is
the ``\\x00`` — ``blob[off : off+len+1]`` is ``k+b"\\x00"`` verbatim
(when ``len == C`` the first byte of the length word is 0, because
C < 2^24).

Per transaction the client ships four blobs (read/write × point/range)
plus counts; the proxy concatenates blobs across the batch with
``b"".join`` and derives every offset from cumsums — no per-key touch
server-side. Keys longer than C bytes don't flatten (the conservative
prefix widening would be lossy on the wire); those transactions ride
the legacy path unchanged.

Kept in ``core`` so the wire codec can name :class:`FlatConflicts`
without importing the resolver stack (and with it JAX).
"""

import struct
from typing import NamedTuple

import numpy as np

_U32 = struct.Struct(">I")

# per-num_limbs encode tables: (zero padding, length words 0..C)
_ENC_TABS = {}


def _tabs(num_limbs):
    t = _ENC_TABS.get(num_limbs)
    if t is None:
        cap = 4 * num_limbs
        t = (b"\x00" * cap, [_U32.pack(n) for n in range(cap + 1)])
        _ENC_TABS[num_limbs] = t
    return t


def entry_width(num_limbs):
    """Bytes per encoded key entry: C key bytes + the 4-byte length."""
    return 4 * num_limbs + 4


class FlatConflicts(NamedTuple):
    """One transaction's conflict ranges, pre-encoded client-side.

    ``*_points`` count point keys (single-key ranges ``[k, k+\\x00)``),
    each one ``entry_width`` bytes in its blob; ``*_ranges`` count true
    ranges, each ``2 * entry_width`` bytes (lower || upper). A tuple
    subclass so the proxy's batch build can unzip a whole request batch
    with one C-speed ``zip(*...)``."""

    num_limbs: int
    read_points: int
    read_point_blob: bytes
    read_ranges: int
    read_range_blob: bytes
    write_points: int
    write_point_blob: bytes
    write_ranges: int
    write_range_blob: bytes


def encode_entry(key, num_limbs):
    """``entry(key)``, or None when the key exceeds limb capacity."""
    pad, lens = _tabs(num_limbs)
    n = len(key)
    if n > 4 * num_limbs:
        return None
    return key + pad[n:] + lens[n]


def _encode_side(ranges, num_limbs, pad, lens):
    """One side's (points, point_blob, ranges, range_blob), or None on
    an over-capacity key. The point test mirrors proxy._split_ranges:
    ``[k, k+\\x00)`` without building the successor bytes."""
    cap = 4 * num_limbs
    pts = []
    rgs = []
    for b, e in ranges:
        nb = len(b)
        if len(e) == nb + 1 and e[-1] == 0 and e.startswith(b):
            # a point stores only its begin entry, so only the KEY must
            # fit — an exactly-capacity point's end (cap+1 bytes) costs
            # nothing (the entry's length word supplies its \x00)
            if nb > cap:
                return None
            pts.append(b + pad[nb:] + lens[nb])
        else:
            if nb > cap or len(e) > cap:
                return None
            rgs.append(b + pad[nb:] + lens[nb])
            ne = len(e)
            rgs.append(e + pad[ne:] + lens[ne])
    return len(pts), b"".join(pts), len(rgs) // 2, b"".join(rgs)


def encode_conflicts(read_ranges, write_ranges, num_limbs):
    """Encode a transaction's conflict ranges → FlatConflicts, or None
    when any key exceeds the 4*num_limbs-byte limb capacity (the legacy
    path handles those with its conservative widening)."""
    pad, lens = _tabs(num_limbs)
    r = _encode_side(read_ranges, num_limbs, pad, lens)
    if r is None:
        return None
    w = _encode_side(write_ranges, num_limbs, pad, lens)
    if w is None:
        return None
    return FlatConflicts(num_limbs, *r, *w)


def point_limbs(blob, num_limbs):
    """uint32[n_entries, W] native-order limb rows (one frombuffer
    pass — this IS KeyCodec.encode_lower_batch's output)."""
    W = num_limbs + 1
    if not blob:
        return np.zeros((0, W), dtype=np.uint32)
    return np.frombuffer(blob, dtype=">u4").reshape(-1, W).astype(
        np.uint32)


def range_limbs(blob, num_limbs):
    """(lower uint32[n, W], upper uint32[n, W]) limb rows."""
    W = num_limbs + 1
    if not blob:
        z = np.zeros((0, W), dtype=np.uint32)
        return z, z
    a = np.frombuffer(blob, dtype=">u4").reshape(-1, 2, W).astype(
        np.uint32)
    return a[:, 0], a[:, 1]


def _decode_entries(blob, num_limbs):
    """entry blob → list[bytes] raw keys (exact: in-capacity only)."""
    w = entry_width(num_limbs)
    if not blob:
        return []
    lens = np.frombuffer(blob, dtype=">u4").reshape(-1,
                                                    num_limbs + 1)[:, -1]
    return [
        blob[o: o + n]
        for o, n in zip(range(0, len(blob), w), lens.tolist())
    ]


def decode_side(point_blob, range_blob, num_limbs):
    """Reconstruct ``[(begin, end)]`` from one side's blobs (points as
    ``[k, k+\\x00)``) — the wire's lazy fallback for consumers that
    still want byte ranges (cpu resolver, conflicting-keys reports)."""
    out = [(k, k + b"\x00") for k in _decode_entries(point_blob,
                                                     num_limbs)]
    ks = _decode_entries(range_blob, num_limbs)
    out.extend(zip(ks[0::2], ks[1::2]))
    return out


class FlatTxnBatch:
    """One commit batch, columnar: per-txn counts + concatenated entry
    blobs (the proxy's ``b"".join`` over FlatConflicts). Consumed
    directly by BatchPacker.pack_flat_group (limb view) and
    NativeConflictSet.resolve_flat (raw-byte view into the same
    blobs)."""

    __slots__ = ("num_limbs", "rv", "prc", "pwc", "rrc", "rwc",
                 "pr_blob", "pw_blob", "rr_blob", "rw_blob", "_txn_memo")

    def __init__(self, num_limbs, rv, prc, pwc, rrc, rwc,
                 pr_blob, pw_blob, rr_blob, rw_blob):
        self._txn_memo = {}  # i -> decoded TxnRequest (see __getitem__)
        self.num_limbs = num_limbs
        self.rv = rv  # int64[n] absolute read versions
        self.prc = prc  # int64[n] point-read counts
        self.pwc = pwc
        self.rrc = rrc  # int64[n] range-read counts
        self.rwc = rwc
        self.pr_blob = pr_blob
        self.pw_blob = pw_blob
        self.rr_blob = rr_blob
        self.rw_blob = rw_blob

    def __len__(self):
        return len(self.rv)

    @property
    def pack_bytes(self):
        return (len(self.pr_blob) + len(self.pw_blob)
                + len(self.rr_blob) + len(self.rw_blob))

    def point_limbs(self, blob):
        return point_limbs(blob, self.num_limbs)

    def range_limbs(self, blob):
        return range_limbs(blob, self.num_limbs)

    # ── fallback decode (rare: lane overflow, too-old txns,
    #    report_conflicting_keys) ──
    def __getitem__(self, i):
        memo = self._txn_memo.get(i)
        if memo is not None:
            # per-txn decode memo: report_conflicting_keys (and the
            # repair engine's repeated access behind it) hits each
            # failed index more than once — never re-parse the blobs
            return memo
        from foundationdb_tpu.resolver.skiplist import TxnRequest

        W4 = entry_width(self.num_limbs)
        po = (int(self.prc[:i].sum()), int(self.pwc[:i].sum()))
        ro = (int(self.rrc[:i].sum()), int(self.rwc[:i].sum()))
        pr = _decode_entries(
            self.pr_blob[po[0] * W4: (po[0] + int(self.prc[i])) * W4],
            self.num_limbs)
        pw = _decode_entries(
            self.pw_blob[po[1] * W4: (po[1] + int(self.pwc[i])) * W4],
            self.num_limbs)
        rr = decode_side(b"",
                         self.rr_blob[ro[0] * 2 * W4:
                                      (ro[0] + int(self.rrc[i])) * 2 * W4],
                         self.num_limbs)
        rw = decode_side(b"",
                         self.rw_blob[ro[1] * 2 * W4:
                                      (ro[1] + int(self.rwc[i])) * 2 * W4],
                         self.num_limbs)
        out = self._txn_memo[i] = TxnRequest(
            read_version=int(self.rv[i]),
            point_reads=pr, point_writes=pw,
            range_reads=rr, range_writes=rw,
        )
        return out

    def to_txn_requests(self):
        """The whole batch as legacy TxnRequests (the rare-path escape
        hatch; per-key Python, so callers reserve it for batches the
        flat path can't serve)."""
        return [self[i] for i in range(len(self))]


def build_flat_batch(requests, num_limbs, idmp_key_of=None):
    """Concatenate a request batch's FlatConflicts into one columnar
    FlatTxnBatch — the proxy's flat twin of ``_build_txns``. Returns
    None when any request lacks a matching-width FlatConflicts (the
    caller falls back to the legacy build).

    ``idmp_key_of(request)`` returns the idempotency system row an
    id-carrying request must conflict on (or None); its point entry is
    appended to BOTH sides, mirroring legacy ``_idmp_point``."""
    n = len(requests)
    if n == 0:
        z = np.zeros(0, dtype=np.int64)
        return FlatTxnBatch(num_limbs, z, z, z, z, z, b"", b"", b"", b"")
    fcs = [r.flat_conflicts for r in requests]
    if None in fcs:
        return None
    has_ids = any(
        getattr(r, "idempotency_id", None) is not None for r in requests
    ) and idmp_key_of is not None
    if not has_ids:
        # the hot shape: unzip every column with ONE C-speed zip, no
        # per-request Python beyond the comprehension above
        (nls, rps, rpbs, rrs, rrbs, wps, wpbs, wrs, wrbs) = zip(*fcs)
        if any(nl != num_limbs for nl in nls):
            return None
        rv = np.fromiter(
            (r.read_version for r in requests), dtype=np.int64, count=n
        )
        return FlatTxnBatch(
            num_limbs, rv,
            np.fromiter(rps, np.int64, count=n),
            np.fromiter(wps, np.int64, count=n),
            np.fromiter(rrs, np.int64, count=n),
            np.fromiter(wrs, np.int64, count=n),
            b"".join(rpbs), b"".join(wpbs),
            b"".join(rrbs), b"".join(wrbs),
        )
    prc = np.empty(n, dtype=np.int64)
    pwc = np.empty(n, dtype=np.int64)
    rrc = np.empty(n, dtype=np.int64)
    rwc = np.empty(n, dtype=np.int64)
    rv = np.empty(n, dtype=np.int64)
    pr_parts = []
    pw_parts = []
    rr_parts = []
    rw_parts = []
    for i, r in enumerate(requests):
        f = r.flat_conflicts
        if f.num_limbs != num_limbs:
            return None
        ik = idmp_key_of(r)
        if ik is None:
            prc[i] = f.read_points
            pwc[i] = f.write_points
            pr_parts.append(f.read_point_blob)
            pw_parts.append(f.write_point_blob)
        else:
            e = encode_entry(ik, num_limbs)
            if e is None:
                return None  # over-capacity idmp key: legacy path
            prc[i] = f.read_points + 1
            pwc[i] = f.write_points + 1
            pr_parts.append(f.read_point_blob + e)
            pw_parts.append(f.write_point_blob + e)
        rrc[i] = f.read_ranges
        rwc[i] = f.write_ranges
        rr_parts.append(f.read_range_blob)
        rw_parts.append(f.write_range_blob)
        rv[i] = r.read_version
    return FlatTxnBatch(
        num_limbs, rv, prc, pwc, rrc, rwc,
        b"".join(pr_parts), b"".join(pw_parts),
        b"".join(rr_parts), b"".join(rw_parts),
    )
