"""Per-transaction resolution statuses — single source of truth.

Ref: ConflictBatch::TransactionCommitted / TransactionConflict /
TransactionTooOld in fdbserver/SkipList.cpp.
"""

COMMITTED = 0
CONFLICT = 1
TOO_OLD = 2
