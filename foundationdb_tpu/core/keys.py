"""Key model: byte keys, ranges, selectors, and the TPU limb encoding.

FoundationDB keys are arbitrary byte strings (<= 10 kB), ordered
lexicographically (ref: fdbclient/FDBTypes.h KeyRef; key limits in
fdbclient/Knobs.h). The TPU resolver cannot chase pointers over variable
length strings, so keys crossing into the conflict kernel are encoded as
fixed-width vectors of uint32 *limbs* plus a length limb:

    E(k) = (limb_0, ..., limb_{L-1}, len(k))        for len(k) <= 4*L

Each limb packs 4 key bytes big-endian, zero-padded, so comparing encoded
vectors lexicographically (limbs first, length last) matches byte-string
order exactly for in-capacity keys: zero padding conflates b"ab" with
b"ab\\x00" at the limb level, and the trailing length limb breaks that tie
in the right direction.

Keys longer than the capacity are *rounded conservatively*: lower bounds
round down to their 4L-byte prefix and upper bounds round up to the
prefix's 256-bit successor. Widening a read or write conflict range can
only introduce false conflicts (a spurious retry), never a missed one —
the same safety direction FDB itself leans on (e.g. conflict ranges are
allowed to over-approximate; ref: ReadYourWrites.actor.cpp conflict-range
accrual).
"""

import numpy as np

MAX_KEY_SIZE = 10_000  # bytes; ref: CLIENT_KNOBS->KEY_SIZE_LIMIT
MAX_VALUE_SIZE = 100_000  # ref: CLIENT_KNOBS->VALUE_SIZE_LIMIT
DEFAULT_LIMBS = 8  # 32-byte exact prefix; tune per workload


class KeyCodec:
    """Encodes byte keys into fixed-width uint32 limb vectors.

    ``width`` = num_limbs + 1 (trailing length limb). All encoded arrays
    have dtype uint32 and compare lexicographically elementwise.
    """

    def __init__(self, num_limbs=DEFAULT_LIMBS):
        assert num_limbs >= 1
        self.num_limbs = int(num_limbs)
        self.capacity = 4 * self.num_limbs
        self.width = self.num_limbs + 1

    def _pack(self, key):
        limbs = np.zeros(self.width, dtype=np.uint32)
        data = key[: self.capacity]
        padded = data + b"\x00" * (self.capacity - len(data))
        limbs[: self.num_limbs] = np.frombuffer(padded, dtype=">u4").astype(np.uint32)
        return limbs

    def encode_lower(self, key):
        """Encode a lower (inclusive-begin) bound; rounds down if too long."""
        limbs = self._pack(key)
        limbs[-1] = min(len(key), self.capacity)
        return limbs

    def encode_upper(self, key):
        """Encode an upper (exclusive-end) bound; rounds up if too long."""
        limbs = self._pack(key)
        if len(key) <= self.capacity:
            limbs[-1] = len(key)
            return limbs
        # Successor of the 4L-byte prefix, as a 32L-bit increment.
        for i in range(self.num_limbs - 1, -1, -1):
            if limbs[i] != 0xFFFFFFFF:
                limbs[i] += np.uint32(1)
                limbs[i + 1 : self.num_limbs] = 0
                limbs[-1] = 0
                return limbs
            limbs[i] = 0
        # All-0xFF prefix: saturate above every encodable key.
        limbs[: self.num_limbs] = np.uint32(0xFFFFFFFF)
        limbs[-1] = np.uint32(self.capacity + 1)
        return limbs

    def encode_point(self, key):
        """Encode point key k as the widened range [lower(k), upper(k+\\x00))."""
        return self.encode_lower(key), self.encode_upper(key + b"\x00")

    def _pack_batch(self, keys):
        """keys: list[bytes] → (uint32[n, W] with zeroed length limb,
        int64[n] true lengths). One frombuffer over the joined padded
        bytes replaces n per-key array constructions."""
        n = len(keys)
        C, L = self.capacity, self.num_limbs
        # in-capacity keys (the common case) pad with one copy; only
        # over-capacity keys pay a truncating slice. A listcomp feeds
        # join measurably faster than a genexpr.
        buf = b"".join(
            [k.ljust(C, b"\x00") if len(k) <= C else k[:C] for k in keys]
        )
        out = np.zeros((n, self.width), dtype=np.uint32)
        if n:
            out[:, :L] = (
                np.frombuffer(buf, dtype=">u4").reshape(n, L).astype(np.uint32)
            )
        lens = np.fromiter(map(len, keys), dtype=np.int64, count=n)
        return out, lens

    def encode_lower_batch(self, keys):
        """Vectorized encode_lower: list[bytes] → uint32[n, W]."""
        out, lens = self._pack_batch(keys)
        out[:, -1] = np.minimum(lens, self.capacity).astype(np.uint32)
        return out

    def encode_bounds_batch(self, begins, ends):
        """Both bounds of n ranges in ONE packing pass → (lower[n, W],
        upper[n, W]). encode_lower and encode_upper agree for in-capacity
        keys (length limb = len), so a single joined encode covers both
        halves; only over-capacity upper bounds take the scalar
        prefix-successor fixup."""
        nb = len(begins)
        out, lens = self._pack_batch(list(begins) + list(ends))
        out[:, -1] = np.minimum(lens, self.capacity).astype(np.uint32)
        long = np.nonzero(lens[nb:] > self.capacity)[0]
        for i in long:
            out[nb + i] = self.encode_upper(ends[i])
        return out[:nb], out[nb:]

    def encode_range(self, begin, end):
        return self.encode_lower(begin), self.encode_upper(end)

    def max_sentinel(self):
        """An encoded value strictly greater than every encodable key."""
        limbs = np.full(self.width, 0xFFFFFFFF, dtype=np.uint32)
        return limbs


def key_successor(key):
    """Smallest key strictly greater than ``key``: key + b'\\x00'.

    Ref: keyAfter() in fdbclient/FDBTypes.h.
    """
    return bytes(key) + b"\x00"


def strinc(key):
    """Smallest key not prefixed by ``key``.

    Ref: strinc() in flow/flow.h — increments the last non-0xFF byte and
    truncates; used for prefix ranges (subspace.range()).
    """
    key = bytes(key)
    stripped = key.rstrip(b"\xff")
    if not stripped:
        raise ValueError("strinc of all-0xFF key has no successor")
    return stripped[:-1] + bytes([stripped[-1] + 1])


class KeyRange:
    """Half-open byte-key range [begin, end). Ref: KeyRangeRef in FDBTypes.h."""

    __slots__ = ("begin", "end")

    def __init__(self, begin, end):
        begin, end = bytes(begin), bytes(end)
        if begin > end:
            from foundationdb_tpu.core.errors import err

            raise err("inverted_range")
        self.begin = begin
        self.end = end

    @classmethod
    def single_key(cls, key):
        return cls(key, key_successor(key))

    @classmethod
    def prefix(cls, p):
        return cls(p, strinc(p))

    def __contains__(self, key):
        return self.begin <= bytes(key) < self.end

    def intersects(self, other):
        return self.begin < other.end and other.begin < self.end

    def empty(self):
        return self.begin == self.end

    def __eq__(self, other):
        return (
            isinstance(other, KeyRange)
            and self.begin == other.begin
            and self.end == other.end
        )

    def __hash__(self):
        return hash((self.begin, self.end))

    def __repr__(self):
        return f"KeyRange({self.begin!r}, {self.end!r})"


class KeySelector:
    """FDB key selector: resolved against the database's key order.

    Ref: KeySelectorRef in fdbclient/FDBTypes.h and resolveKey in
    storageserver.actor.cpp. Semantics: start from the last key <= (or <)
    the reference key, then move ``offset`` keys forward.
    """

    __slots__ = ("key", "or_equal", "offset")

    def __init__(self, key, or_equal, offset):
        self.key = bytes(key)
        self.or_equal = bool(or_equal)
        self.offset = int(offset)

    @classmethod
    def last_less_than(cls, key):
        return cls(key, False, 0)

    @classmethod
    def last_less_or_equal(cls, key):
        return cls(key, True, 0)

    @classmethod
    def first_greater_than(cls, key):
        return cls(key, True, 1)

    @classmethod
    def first_greater_or_equal(cls, key):
        return cls(key, False, 1)

    def __add__(self, n):
        return KeySelector(self.key, self.or_equal, self.offset + n)

    def __sub__(self, n):
        return KeySelector(self.key, self.or_equal, self.offset - n)

    def __repr__(self):
        return f"KeySelector({self.key!r}, or_equal={self.or_equal}, offset={self.offset})"
