"""Injectable entropy + clock — the sim-determinism seam.

Ref parity: FoundationDB's deterministic simulation works only because
every source of nondeterminism the cluster can OBSERVE flows through
``deterministicRandom()`` and ``g_network->now()``, which sim2 seeds and
replays (flow/IRandom.h, fdbrpc/sim2.actor.cpp). Cluster-visible code
never calls the OS clock or OS entropy directly; it asks the injected
authority, so a seed replays byte-identically.

This module is that authority for the Python port. Cluster-visible code
draws randomness from a NAMED stream (``rng("proposer-id")``) and reads
time via ``now()``:

- **Production** (default): streams are seeded from OS entropy and
  ``now()`` is the wall clock — behavior is unchanged from calling
  ``random`` / ``time.time`` directly.
- **Simulation**: ``sim/simulation.py`` calls ``seed(master_seed)`` and
  ``set_clock(step_clock)`` at cluster build; every stream re-seeds to a
  value derived from (master seed, stream name), so two same-seed runs
  draw identical proposer ids, directory prefixes, idempotency ids, …

Named streams (rather than one shared stream) keep call sites
independent: adding a draw in one subsystem does not shift another
subsystem's sequence, which keeps seed replays stable across unrelated
code changes — the same reason the reference hands each actor its own
DeterministicRandom fork.

flowlint's FL001 rule enforces the seam: direct ``time.time()`` /
``os.urandom`` / module-level ``random.*`` calls outside ``sim/`` (and
this module) are findings. Deliberately non-deterministic sites —
crypto material like the RPC auth nonce — stay on ``os.urandom`` with
an inline ``# flowlint: disable=FL001`` and a stated reason: feeding an
attacker-predictable seeded stream into authentication would be a
vulnerability, and the sim never exercises the real transport.
"""

import random
import threading
import time
from foundationdb_tpu.utils import lockdep


class DeterminismRegistry:
    """Named RNG streams + an injectable clock, one per process."""

    def __init__(self):
        self._lock = lockdep.lock("DeterminismRegistry._lock")
        self._streams = {}
        self._seed = None  # None = production mode (OS entropy)
        self._clock = time.time

    # ── entropy ──
    def rng(self, name):
        """The named stream (a persistent ``random.Random``). The same
        name always returns the same object, so a later ``seed()``
        re-seeds every stream handed out earlier — construction order
        and seeding order cannot race."""
        with self._lock:
            stream = self._streams.get(name)
            if stream is None:
                if self._seed is None:
                    stream = random.Random()  # OS-entropy seeded
                else:
                    stream = random.Random(f"{self._seed}:{name}")
                self._streams[name] = stream
            return stream

    def token_bytes(self, n, name="token"):
        """``n`` random bytes from a named stream (idempotency ids,
        generated cluster ids). Deterministic under a seed; OS-entropy
        quality in production. NOT for cryptographic material — auth
        nonces must stay on ``os.urandom``."""
        return self.rng(name).getrandbits(8 * n).to_bytes(n, "big")

    def seed(self, master_seed):
        """Enter deterministic mode: every existing stream re-seeds to
        hash(master_seed, name); streams created later derive the same
        way. Two processes seeding the same value draw identical
        sequences from identically-named streams."""
        with self._lock:
            self._seed = master_seed
            for name, stream in self._streams.items():
                stream.seed(f"{master_seed}:{name}")

    def unseed(self):
        """Back to production mode: streams re-seed from OS entropy."""
        with self._lock:
            self._seed = None
            for stream in self._streams.values():
                stream.seed()

    @property
    def seeded(self):
        return self._seed is not None

    # ── time ──
    def now(self):
        """The injected clock (wall clock in production; the sim's step
        clock under simulation)."""
        return self._clock()

    def set_clock(self, fn):
        self._clock = fn

    def reset_clock(self):
        self._clock = time.time


_registry = DeterminismRegistry()


def registry():
    return _registry


def rng(name):
    return _registry.rng(name)


def token_bytes(n, name="token"):
    return _registry.token_bytes(n, name)


def seed(master_seed):
    _registry.seed(master_seed)


def unseed():
    _registry.unseed()


def now():
    # reads the clock through the registry's live slot (not a cached
    # fn) so set_clock/reset_clock swaps take effect, while skipping
    # the method hop — this sits on per-operation hot paths (metrics
    # stamps, span begin/end)
    return _registry._clock()


def set_clock(fn):
    _registry.set_clock(fn)
