"""CommitRequest — what a client sends at commit.

Ref parity: CommitTransactionRequest (fdbclient/CommitTransaction.h).
Lives in core (not server/proxy.py, which re-exports it) so that
dependency-light consumers — the wire codec, coordinator-only server
processes — can name the type without pulling the resolver stack (and
with it JAX) into their import graph.
"""


class CommitRequest:
    __slots__ = ("read_version", "mutations", "read_conflict_ranges",
                 "write_conflict_ranges", "report_conflicting_keys",
                 "lock_aware", "idempotency_id")

    def __init__(self, read_version, mutations, read_conflict_ranges,
                 write_conflict_ranges, report_conflicting_keys=False,
                 lock_aware=False, idempotency_id=None):
        self.read_version = read_version
        self.mutations = mutations
        self.read_conflict_ranges = read_conflict_ranges  # [(begin, end)]
        self.write_conflict_ranges = write_conflict_ranges
        self.report_conflicting_keys = report_conflicting_keys
        # ref: FDBTransactionOptions LOCK_AWARE — this txn commits even
        # while the database is locked (lockDatabase in ManagementAPI)
        self.lock_aware = lock_aware
        # ref: fdbclient/IdempotencyId.actor.cpp — a client-chosen token
        # carried with the commit; the proxy records it atomically with
        # the mutations and dedupes resubmissions, so a retry after 1021
        # cannot double-apply
        self.idempotency_id = idempotency_id
