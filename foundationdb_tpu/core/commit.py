"""CommitRequest — what a client sends at commit.

Ref parity: CommitTransactionRequest (fdbclient/CommitTransaction.h).
Lives in core (not server/proxy.py, which re-exports it) so that
dependency-light consumers — the wire codec, coordinator-only server
processes — can name the type without pulling the resolver stack (and
with it JAX) into their import graph.

``flat_conflicts`` (core/flatpack.py) is the columnar fast path: the
client pre-encodes its conflict ranges into limb-entry blobs, and the
wire's columnar frame ships ONLY those — the byte-pair range lists are
then reconstructed lazily, on the rare paths that still want them
(cpu-backend resolution, conflicting-keys reports). Both forms describe
the same ranges; the flat one exists only for in-capacity keys, so the
reconstruction is exact.
"""


class CommitRequest:
    __slots__ = ("read_version", "mutations", "_read_conflict_ranges",
                 "_write_conflict_ranges", "report_conflicting_keys",
                 "lock_aware", "idempotency_id", "flat_conflicts",
                 "span_context", "tags")

    def __init__(self, read_version, mutations, read_conflict_ranges,
                 write_conflict_ranges, report_conflicting_keys=False,
                 lock_aware=False, idempotency_id=None,
                 flat_conflicts=None, span_context=None, tags=()):
        self.read_version = read_version
        self.mutations = mutations
        self._read_conflict_ranges = read_conflict_ranges  # [(begin, end)]
        self._write_conflict_ranges = write_conflict_ranges
        self.report_conflicting_keys = report_conflicting_keys
        # ref: FDBTransactionOptions LOCK_AWARE — this txn commits even
        # while the database is locked (lockDatabase in ManagementAPI)
        self.lock_aware = lock_aware
        # ref: fdbclient/IdempotencyId.actor.cpp — a client-chosen token
        # carried with the commit; the proxy records it atomically with
        # the mutations and dedupes resubmissions, so a retry after 1021
        # cannot double-apply
        self.idempotency_id = idempotency_id
        self.flat_conflicts = flat_conflicts
        # distributed tracing (utils/span.py): the client commit span's
        # (trace_id, span_id, sampled) context — the commit path's
        # propagation vehicle, since batched requests from many traced
        # transactions share one wire frame / batcher queue. None for
        # untraced (or unsampled) transactions.
        self.span_context = span_context
        # workload attribution (ref: TransactionTagRef on
        # CommitTransactionRequest): the client's set_tag() labels, so
        # the proxy can attribute this commit/abort/conflict per tag
        self.tags = tuple(tags) if tags else ()

    @property
    def read_conflict_ranges(self):
        # memoized on the request: the flat-path decode runs at most
        # once per side, so the repair engine's (and the scheduler's)
        # repeated access never re-parses the blobs
        r = self._read_conflict_ranges
        if r is None:
            r = self._read_conflict_ranges = self._from_flat("read")
        return r

    @read_conflict_ranges.setter
    def read_conflict_ranges(self, v):
        self._read_conflict_ranges = v

    @property
    def write_conflict_ranges(self):
        w = self._write_conflict_ranges
        if w is None:
            w = self._write_conflict_ranges = self._from_flat("write")
        return w

    @write_conflict_ranges.setter
    def write_conflict_ranges(self, v):
        self._write_conflict_ranges = v

    def _from_flat(self, side):
        """Reconstruct a byte-pair range list from the columnar form (a
        request decoded from the wire's columnar frame carries only
        that). Point order may differ from the client's original list —
        the resolver is order-independent within a transaction."""
        f = self.flat_conflicts
        if f is None:
            return []
        from foundationdb_tpu.core import flatpack

        if side == "read":
            return flatpack.decode_side(
                f.read_point_blob, f.read_range_blob, f.num_limbs)
        return flatpack.decode_side(
            f.write_point_blob, f.write_range_blob, f.num_limbs)
