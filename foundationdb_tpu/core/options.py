"""Knobs — tunable constants, mirroring flow/Knobs.h / fdbclient/Knobs.h.

The headline knob is ``resolver_backend``: ``"tpu"`` routes conflict
detection through the JAX kernel (ops/conflict.py); ``"cpu"`` uses the
SkipList-style host ConflictSet (resolver/skiplist.py), matching the
reference's default path.
"""

import dataclasses


@dataclasses.dataclass
class Knobs:
    # --- resolver ---
    resolver_backend: str = "tpu"  # "tpu" | "cpu" (python) | "native" (C++)
    batch_txn_capacity: int = 1024  # T: txns per resolver batch (static shape)
    point_reads_per_txn: int = 4  # PR
    point_writes_per_txn: int = 4  # PW
    range_reads_per_txn: int = 2  # RR
    range_writes_per_txn: int = 2  # RW
    hash_table_bits: int = 22  # point-write version table: 2^bits entries
    range_ring_capacity: int = 4096  # recent range-write ring (exact lane)
    coarse_buckets_bits: int = 14  # 2^bits contiguous key buckets (coarse lane)
    ring_partition_bits: int = 0  # 2^bits bucket-partitioned sub-rings
    # (0 = flat ring; >0 cuts range-check work ~2/2^bits on one device)
    key_limbs: int = 8  # 4*L bytes of exact key prefix on device
    # ring lanes via the Pallas VMEM kernel (ops/pallas_ring.py):
    # "auto" = on TPU backends, "on" = everywhere (interpreter off-TPU,
    # for differential tests), "off" = always the jnp lanes
    pallas_ring: str = "auto"
    # the FULL per-batch accept step as one fused Pallas kernel
    # (ops/pallas_scan.py): exact ring check + intra-batch segment
    # intersection + greedy acceptance in VMEM, subsuming pallas_ring's
    # lane when engaged. Same tri-state as pallas_ring; auto-gates off
    # when the static shape is ineligible (txns > 1024, partitioned
    # ring) and falls back to the jit path under the pallas_to_jit
    # taxonomy on lowering errors.
    pallas_scan: str = "auto"
    # mesh lane ownership (resolver/meshresolver.py, multi-lane tpu
    # fleets only): "range" routes each packed entry host-side to the
    # lane(s) owning its key range (resolver/packing.ShardRouter) and
    # runs the compacted single-dispatch kernel — per-lane work shrinks
    # ~1/n, the path that makes k lanes faster than one. "hash"
    # replicates the batch and carves ownership in-kernel (hash-sharded
    # point table, bucket-sharded ring): no host routing pass, no work
    # reduction.
    resolver_sharding: str = "range"
    # commit-path host packing (core/flatpack.py): "flat" = the client
    # pre-encodes conflict ranges into columnar limb blobs and the
    # proxy/packer consume them without per-txn Python ("legacy" keeps
    # the TxnRequest object path). Flat engages per batch only when
    # every request carries matching-width blobs and the resolver
    # accepts them (tpu/native, single resolver); everything else
    # falls back to legacy with identical packed arrays
    # (tests/test_packing_flat.py).
    commit_pack_path: str = "flat"

    # --- conflict repair & abort-aware batch scheduling ---
    # proxy-side intra-batch scheduling (server/scheduler.py): reorder a
    # commit batch host-side — over the clients' already-encoded flat
    # limb blobs, before packing — so reads resolve before the writes
    # they overlap and the resolver sees fewer self-inflicted aborts.
    # Default ON: the same-seed sim differential (tests/test_repair.py)
    # proved byte-identical final state against the arrival-order
    # baseline on both storage engines, so the reorder is free
    # correctness-wise and strictly reduces in-batch aborts.
    commit_batch_scheduling: bool = True
    # client-side transaction repair (txn/repair.py): on not_committed
    # with conflicting-key info, re-read ONLY the conflicting keys at
    # the failed batch's commit version and either replay the recorded
    # op log (read-set digest match — a spurious conflict) or fall back
    # to the retry loop seeded with the verified read cache. Default ON
    # under the same differential as commit_batch_scheduling: repaired
    # retries reach the identical final state the restart loop does,
    # with fewer storage round trips per conflict.
    txn_repair: bool = True
    # consecutive repair rounds before a conflicted transaction falls
    # back to the full cold restart (fresh GRV + backoff sleep) — the
    # livelock bound on the no-backoff repair retry
    txn_repair_max_rounds: int = 4

    # --- versions / MVCC ---
    # (the version rate itself is core.versions.VERSIONS_PER_SECOND —
    # a protocol constant, not a tunable)
    max_read_transaction_life_versions: int = 5_000_000

    # --- transaction limits (ref: fdbclient/Knobs.h CLIENT_KNOBS) ---
    key_size_limit: int = 10_000
    value_size_limit: int = 100_000
    transaction_size_limit: int = 10_000_000

    # --- retry loop (ref: CLIENT_KNOBS backoff) ---
    max_retry_delay_s: float = 1.0
    initial_backoff_s: float = 0.01
    backoff_growth: float = 2.0

    # --- proxy batching ---
    commit_batch_interval_s: float = 0.0005
    grv_batch_interval_s: float = 0.0005
    # bounded commit-pipeline depth (server/batcher.py): how many backlog
    # groups may be in flight at once — group N+1 packs on the host and
    # dispatches its resolve while group N's tlog push + storage apply
    # runs. 1 = the strictly serial loop (exactly the pre-pipeline
    # behavior); manual/sim mode always runs depth 1 for determinism.
    commit_pipeline_depth: int = 2
    # fleet VersionGate stall bound: a turn unclaimed this long means a
    # peer proxy died between grant and advance → 1021 + txn-system
    # recovery (tests shrink it; see server/proxy.py GateTimeout)
    gate_timeout_s: float = 60.0

    # --- read batching (txn/futures.py) ---
    # client-side multiplexed read batching: outstanding async reads on
    # one connection coalesce into single read_batch RPCs (ref:
    # NativeAPI serving every read through futures). max_keys bounds
    # one flush; window_ms is an optional linger after the first wake
    # (0 = flush whatever is queued immediately — the measured-best
    # default: async issue order already coalesces a client window).
    # Manual/sim pipelines always flush immediately for determinism.
    read_batch_max_keys: int = 128
    read_batch_window_ms: float = 0.0
    # CPython thread-switch interval for server processes
    # (tools/fdbserver.py): a waiting read-RPC thread is scheduled only
    # every switch interval, so under commit load the default 5ms adds
    # whole slices to every synchronous read RTT (measured ~25% of the
    # loaded read cost at 0.5ms vs 5ms).
    server_switch_interval_s: float = 0.0005

    # --- distributed tracing (utils/span.py) ---
    # fraction of transactions that carry a sampled trace (0 = tracing
    # off; `fdbcli tracing on` / \xff\xff/tracing/enabled turns it to
    # the 0.01 default-when-enabled). Sampling draws ride the seeded
    # "span-sample" deterministic stream.
    tracing_sample_rate: float = 0.0
    # error/slow-commit promotion: an UNSAMPLED (but tracing-enabled)
    # transaction whose commit aborts or outlives this bound emits its
    # client-side buffered spans anyway
    tracing_slow_commit_ms: float = 200.0

    # --- workload attribution (utils/heatmap.py) ---
    # default-ON key sampling: conflict heat charged at the proxy's
    # abort-fabrication site, read/write heat sampled storage-side.
    # BENCH_MODE=heatmap_smoke measures the enabled-vs-kill-switch cost
    # and gates it at <=2% like metrics_smoke.
    workload_sampling: bool = True
    # bounded histogram state: adjacent-range coalescing keeps each
    # heatmap at most this many buckets no matter how long the run
    heatmap_max_buckets: int = 64
    # exponential decay half-life (injected-clock seconds): old heat
    # fades so the snapshot reflects the CURRENT hot set
    heatmap_half_life_s: float = 30.0
    # storage-side read/write key sampling rate: one sampled key per
    # this many accesses on average (ref: StorageMetrics byte-sampling;
    # draws ride the "key-sample" deterministic stream). Charge weight
    # scales by the stride, so heat stays an unbiased estimate of total
    # accesses; 16 keeps the sampler inside the 2% overhead budget.
    storage_sample_every: int = 16

    # --- cluster doctor (server/health.py, tools/doctor.py) ---
    # latency prober: real GRV/read/commit probe transactions against
    # the live cluster (ref: Status.actor.cpp latencyProbe). Cadence
    # rides the injected clock + the "latency-probe" deterministic
    # stream; thread-mode clusters drive it from a daemon loop, sims
    # call maybe_probe() from their own schedule.
    health_probe_enabled: bool = True
    health_probe_interval_s: float = 1.0
    # doctor SLO thresholds (tools/doctor.py alerts + the storage_lag
    # degraded reason in the health verdict): probe p99 bounds, max
    # acceptable recovery duration, max storage durability lag
    doctor_probe_p99_ms: float = 1000.0
    doctor_recovery_ms: float = 30_000.0
    doctor_lag_versions: int = 5_000_000

    # --- metrics history + flight recorder (utils/timeseries.py) ---
    # cluster-owned retention layer (ref: flow/TDMetric.actor.h
    # continuous metric logging): one fixed-cadence window per interval
    # samples every role registry, the heatmaps, the device profiles,
    # the ratekeeper gauges, and the health verdict into bounded
    # per-metric rings. Cadence rides the injected clock + the
    # "history-cadence" deterministic stream (the FL001 seam, same as
    # the latency prober); thread-mode clusters drive it from a daemon
    # loop, sims call maybe_collect() from their own schedule.
    history_enabled: bool = True
    history_cadence_s: float = 1.0
    history_windows: int = 64  # per-metric ring depth
    history_heat_top: int = 8  # hot-range rows retained per dim/window
    # flight recorder (the black box): verdict transitions, recovery
    # triggers, and probe-SLO breaches dump a bounded artifact — last
    # flight_windows windows + the trace-ring tail + the recovery
    # timeline + activated SimBuggifySites — into an in-memory ring
    # (the \xff\xff/status/flight special key) and, when flight_dir is
    # set, as sorted-key flight-<seq>.json files (byte-identical under
    # a sim seed — the chaos post-mortem contract)
    flight_windows: int = 16
    flight_trace_tail: int = 64
    flight_max_dumps: int = 8
    flight_dir: str = ""
    # trend-aware doctor alerts (tools/doctor.py --trend + the
    # probe_trend degraded reason): a probe p99 strictly rising across
    # this many consecutive windows by at least this total percentage
    # alerts BEFORE the instant doctor_probe_p99_ms threshold breaches
    doctor_trend_windows: int = 3
    doctor_trend_min_rise_pct: float = 5.0

    # --- continuous consistency scan (server/consistencyscan.py) ---
    # cluster-owned background replica auditor (ref: fdbserver/
    # ConsistencyScan.actor.cpp): walks the shard map in bounded
    # key-batches at pinned read versions, compares every live replica
    # in the owning team, and re-reads once against the live map before
    # declaring corruption. Cadence rides the injected clock + the
    # "consistency-scan" deterministic stream (the FL001 seam, same as
    # the latency prober); thread-mode clusters drive it from a daemon
    # loop, sims call maybe_scan() from their own schedule.
    consistency_scan_enabled: bool = True
    consistency_scan_interval_s: float = 0.25
    consistency_scan_batch_keys: int = 256
    # sustained read budget: the next batch is deferred until the bytes
    # the last one read have drained at this rate (0 = unpaced)
    scan_rate_bytes_per_s: float = 2_000_000.0
    # doctor --scan SLO: a completed round older than this — or any
    # confirmed inconsistency — exits 1 (tools/doctor.py)
    doctor_scan_max_round_age_s: float = 600.0

    # --- multi-region replication (server/region.py) ---
    # continuous satellite streamer cadence: the RegionReplicator drains
    # the primary log toward the satellite at most once per interval
    # (jittered off the "region-stream" deterministic stream — the same
    # FL001 seam as the latency prober). Thread-mode clusters drive it
    # from a daemon loop; sims call maybe_stream() from their schedule.
    region_stream_interval_s: float = 0.05
    # doctor SLO thresholds for the regions section of cluster.health:
    # replication lag (versions) before the region_lag degraded reason
    # fires, and the longest acceptable region failover duration
    doctor_region_lag_versions: int = 2_000_000
    doctor_region_failover_ms: float = 60_000.0

    # --- per-tag auto-throttling (server/ratekeeper.py) ---
    # admission share above which a tag auto-throttles EVEN WITHOUT
    # global pressure (ref: TagThrottler's standalone busy-tag policy;
    # the under-pressure AIMD path is always on). 1.0 disables the
    # standalone path — a share can never exceed 1.0 — matching the
    # reference's default of auto-throttling being opt-in.
    tag_throttle_busyness: float = 1.0

    # --- RPC deadlines & failure monitor (rpc/transport.py,
    #     rpc/failuremon.py, rpc/service.py) ---
    # per-class RPC deadlines: every remote call carries one, enforced
    # by the client reader thread's deadline sweep (ref: per-request
    # timeouts via flow's timeoutError). An expired commit-class call
    # surfaces as commit_unknown_result (1021 — the txn MAY have
    # committed); read/GRV/admin expiries are plainly retryable (1037).
    rpc_deadline_read_s: float = 5.0
    rpc_deadline_grv_s: float = 5.0
    rpc_deadline_commit_s: float = 15.0
    rpc_deadline_admin_s: float = 30.0
    # per-endpoint health memory (ref: fdbrpc/FailureMonitor.actor.cpp):
    # deadline/ECONNRESET marks the endpoint failed; the read router
    # skips failed replicas; recovery is probed half-open with
    # exponential spacing. Off = every caller rediscovers a dead worker
    # by timing out against it (the pre-monitor behavior).
    failure_monitor: bool = True
    # keepalive ping cadence on idle client links (jittered off the
    # "ping-cadence" deterministic stream); 0 disables the pinger
    rpc_ping_interval_s: float = 2.0
    # chaos transport arming (rpc/chaos.py): a non-empty seed wraps
    # every NEW client socket in the seeded fault injector — test/bench
    # only; "" keeps chaos entirely un-imported (the default path)
    rpc_chaos_seed: str = ""

    # --- simulation ---
    # process-global BUGGIFY default (sim/buggify.py): `buggify` arms
    # the module-level BUGGIFY singleton at import (Simulation always
    # builds its own seeded instance regardless); `buggify_prob` is the
    # default per-evaluation fire probability for sites that do not
    # pass an explicit fire_p.
    buggify: bool = False
    buggify_prob: float = 0.05


DEFAULT_KNOBS = Knobs()
