"""FoundationDB-compatible error model.

Ref parity: flow/Error.h and the generated error list in
fdbclient/vexillographer/fdb.options. Codes match the reference so client
code written against FDB's bindings ports over unchanged.
"""

_ERRORS = {
    0: "success",
    1000: "operation_failed",
    1004: "timed_out",
    1007: "transaction_too_old",
    1009: "future_version",
    1011: "version_invalid",
    1020: "not_committed",
    1021: "commit_unknown_result",
    1025: "transaction_cancelled",
    1031: "transaction_timed_out",
    1037: "process_behind",
    1038: "database_locked",
    1101: "operation_cancelled",
    1213: "tag_throttled",
    2000: "client_invalid_operation",
    2002: "commit_read_incomplete",
    2003: "test_specification_invalid",
    2004: "key_outside_legal_range",
    2005: "inverted_range",
    2006: "invalid_option_value",
    2009: "incompatible_protocol_version",
    2010: "transaction_invalid_version",
    2011: "no_commit_version",
    2017: "used_during_commit",
    2101: "transaction_too_large",
    2102: "key_too_large",
    2103: "value_too_large",
    2108: "tenant_not_found",
    2130: "tenant_name_required",
    2132: "tenant_already_exists",
    2133: "tenant_not_empty",
    2134: "tenants_disabled",
    2144: "tenant_locked",  # mid-move fence (ref: metacluster moves)
    2160: "invalid_metacluster_operation",
    2161: "cluster_already_registered",
    2165: "cluster_not_empty",
    2166: "metacluster_no_capacity",
    2200: "api_version_unset",
}

_BY_NAME = {v: k for k, v in _ERRORS.items()}

# Errors on which the standard retry loop (Transaction.on_error) retries.
# Ref: fdb_error_predicate(FDB_ERROR_PREDICATE_RETRYABLE, ...) in bindings/c.
RETRYABLE = frozenset({1007, 1009, 1020, 1021, 1037, 1213, 2144})
MAYBE_COMMITTED = frozenset({1021})


class FDBError(Exception):
    """An error with an FDB error code. Ref: class Error in flow/Error.h."""

    def __init__(self, code, message=None):
        self.code = int(code)
        self.description = _ERRORS.get(self.code, "unknown_error")
        super().__init__(message or f"{self.description} ({self.code})")

    @classmethod
    def from_name(cls, name):
        return cls(_BY_NAME[name])

    @property
    def is_retryable(self):
        return self.code in RETRYABLE

    @property
    def is_maybe_committed(self):
        return self.code in MAYBE_COMMITTED


def err(name):
    """Raise-ready FDBError by symbolic name, e.g. err('not_committed')."""
    return FDBError.from_name(name)
