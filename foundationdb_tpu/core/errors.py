"""FoundationDB-compatible error model.

Ref parity: flow/Error.h and the generated error list in
fdbclient/vexillographer/fdb.options. Codes match the reference so client
code written against FDB's bindings ports over unchanged.

This registry is also the ground truth for the static error-taxonomy
pass (flowlint FL009): every fabrication site in the tree must use a
code registered here, by symbolic name — raw numeric literals outside
this file fail the lint. The runtime fault-coverage witness
(utils/faultcov.py, the dynamic twin of flowlint FL011) hooks
``FDBError.__init__``: one module-global read when off, a per-site
counter bump when on.
"""

from foundationdb_tpu.utils import faultcov as _faultcov

_ERRORS = {
    0: "success",
    1000: "operation_failed",
    1004: "timed_out",
    1007: "transaction_too_old",
    1009: "future_version",
    1011: "version_invalid",
    1020: "not_committed",
    1021: "commit_unknown_result",
    1025: "transaction_cancelled",
    1031: "transaction_timed_out",
    1037: "process_behind",
    1038: "database_locked",
    1101: "operation_cancelled",
    1213: "tag_throttled",
    2000: "client_invalid_operation",
    2002: "commit_read_incomplete",
    2003: "test_specification_invalid",
    2004: "key_outside_legal_range",
    2005: "inverted_range",
    2006: "invalid_option_value",
    2009: "incompatible_protocol_version",
    2010: "transaction_invalid_version",
    2011: "no_commit_version",
    2017: "used_during_commit",
    2101: "transaction_too_large",
    2102: "key_too_large",
    2103: "value_too_large",
    2108: "tenant_not_found",
    2130: "tenant_name_required",
    2132: "tenant_already_exists",
    2133: "tenant_not_empty",
    2134: "tenants_disabled",
    2144: "tenant_locked",  # mid-move fence (ref: metacluster moves)
    2160: "invalid_metacluster_operation",
    2161: "cluster_already_registered",
    2165: "cluster_not_empty",
    2166: "metacluster_no_capacity",
    2200: "api_version_unset",
}

_BY_NAME = {v: k for k, v in _ERRORS.items()}

# Errors on which the standard retry loop (Transaction.on_error) retries.
# Ref: fdb_error_predicate(FDB_ERROR_PREDICATE_RETRYABLE, ...) in bindings/c.
RETRYABLE = frozenset({1007, 1009, 1020, 1021, 1037, 1213, 2144})
MAYBE_COMMITTED = frozenset({1021})


def registered_codes():
    """Frozen set of every registered error code (FL009's ground truth
    for numeric codes crossing the wire)."""
    return frozenset(_ERRORS)


def registered_names():
    """Frozen set of every registered symbolic error name."""
    return frozenset(_BY_NAME)


def code_for(name):
    """The registered code for a symbolic name, or a clear ValueError
    naming the bad symbol (a bare KeyError names nothing)."""
    try:
        return _BY_NAME[name]
    except KeyError:
        raise ValueError(
            f"unknown FDB error name {name!r} — register it in "
            f"core/errors.py"
        ) from None


def error_name(code):
    """The symbolic name for a code, or 'unknown_error'."""
    return _ERRORS.get(code, "unknown_error")


class FDBError(Exception):
    """An error with an FDB error code. Ref: class Error in flow/Error.h."""

    def __init__(self, code, message=None):
        self.code = int(code)
        self.description = _ERRORS.get(self.code, "unknown_error")
        super().__init__(message or f"{self.description} ({self.code})")
        if _faultcov._enabled:
            _faultcov.note(self.code)

    @classmethod
    def from_name(cls, name, message=None):
        return cls(code_for(name), message)

    @property
    def is_retryable(self):
        return self.code in RETRYABLE

    @property
    def is_maybe_committed(self):
        return self.code in MAYBE_COMMITTED


def err(name, message=None):
    """Raise-ready FDBError by symbolic name, e.g. err('not_committed').
    Unknown names raise ValueError naming the symbol, not KeyError."""
    return FDBError.from_name(name, message)
