"""Version arithmetic and versionstamps.

FDB versions are signed 64-bit integers advancing at ~1e6 per wall second
(ref: SERVER_KNOBS->VERSIONS_PER_SECOND, fdbserver/masterserver.actor.cpp).
On host they are Python ints. On device the resolver stores versions as
uint32 *offsets* from a rolling ``base_version`` — the 5-second MVCC window
(ref: SERVER_KNOBS->MAX_READ_TRANSACTION_LIFE_VERSIONS = 5e6) spans only
5e6 versions, so 32 bits give ~70 minutes of headroom between rebases and
keep all device arithmetic in TPU-friendly 32-bit lanes.
"""

import struct

VERSIONS_PER_SECOND = 1_000_000
MAX_READ_TRANSACTION_LIFE_VERSIONS = 5 * VERSIONS_PER_SECOND
# Rebase the device window well before uint32 offsets can wrap.
REBASE_THRESHOLD = 1 << 30

INVALID_VERSION = -1


class Versionstamp:
    """10-byte versionstamp: 8-byte commit version + 2-byte batch order,
    optionally followed by a 2-byte user suffix in tuple encoding.

    Ref: fdbclient/Versionstamp.h (TupleVersionstamp).
    """

    __slots__ = ("tr_version", "user_version")

    def __init__(self, tr_version=None, user_version=0):
        if tr_version is not None and len(tr_version) != 10:
            raise ValueError("transaction versionstamp must be 10 bytes")
        self.tr_version = tr_version  # None => incomplete (filled at commit)
        self.user_version = int(user_version)

    @classmethod
    def from_version(cls, version, batch_order=0, user_version=0):
        return cls(struct.pack(">qH", version, batch_order), user_version)

    @property
    def complete(self):
        return self.tr_version is not None

    def to_bytes(self):
        tr = self.tr_version if self.complete else b"\xff" * 10
        return tr + struct.pack(">H", self.user_version)

    @classmethod
    def from_bytes(cls, data):
        if len(data) != 12:
            raise ValueError("versionstamp must be 12 bytes")
        tr, user = data[:10], struct.unpack(">H", data[10:])[0]
        vs = cls(tr if tr != b"\xff" * 10 else None, user)
        return vs

    def version(self):
        return struct.unpack(">q", self.tr_version[:8])[0] if self.complete else None

    def __eq__(self, other):
        return (
            isinstance(other, Versionstamp)
            and self.to_bytes() == other.to_bytes()
        )

    def __lt__(self, other):
        return self.to_bytes() < other.to_bytes()

    def __hash__(self):
        return hash(self.to_bytes())

    def __repr__(self):
        return f"Versionstamp({self.tr_version!r}, {self.user_version})"
