"""BUGGIFY: seeded, site-keyed fault activation.

Ref parity: flow/Buggify (the BUGGIFY macro) — each BUGGIFY site is
independently *enabled* for a simulation run with probability
``site_activated_p``; an enabled site then *fires* per evaluation with
probability ``fire_p``. This two-level scheme makes whole failure modes
appear/disappear across seeds, which is what gives FDB simulation its
coverage (a bug that needs faults A+B shows up on seeds where both sites
happen to be enabled).
"""

import random

from foundationdb_tpu.core.options import DEFAULT_KNOBS


class Buggify:
    def __init__(self, seed=0, enabled=True, site_activated_p=0.25, fire_p=None):
        self.enabled = enabled
        self.site_activated_p = site_activated_p
        # default fire probability is the buggify_prob knob
        self.fire_p = DEFAULT_KNOBS.buggify_prob if fire_p is None else fire_p
        self._seed = seed
        self._sites = {}  # site name -> activated?
        self._rng = random.Random(seed ^ 0xB0661F1)

    def __call__(self, site, fire_p=None):
        """True if fault site ``site`` should fire now."""
        if not self.enabled:
            return False
        active = self._sites.get(site)
        if active is None:
            # site activation derives from (seed, site) only — stable no
            # matter the order sites are first evaluated in
            site_rng = random.Random(f"{self._seed}:{site}")
            active = self._sites[site] = site_rng.random() < self.site_activated_p
        return active and self._rng.random() < (
            self.fire_p if fire_p is None else fire_p
        )

    def activated_sites(self):
        return sorted(s for s, a in self._sites.items() if a)


# process-global default: off outside sim unless the buggify knob arms it
BUGGIFY = Buggify(enabled=DEFAULT_KNOBS.buggify)
