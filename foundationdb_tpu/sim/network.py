"""Message-level network simulation.

Ref parity: fdbrpc/sim2.actor.cpp — in the reference's simulation every
RPC is a message delivered after a seeded latency, so requests from
different actors reorder, drop, and stall behind partitions; whole
classes of distributed bugs only manifest under that reordering.

Ours models the client ↔ cluster edge the same way: a call becomes a
message with a seeded delivery delay (in scheduler steps); the simulation
delivers due messages each step in DELIVERY order — not send order — and
the caller's actor yields until its reply future resolves. Drops surface
as retryable errors (commit_unknown_result for commits, since the client
cannot know whether the request reached the proxy). A partition delays
every in-window message until it heals, producing burst reordering.
"""

import heapq

from foundationdb_tpu.core.errors import err


class NetFuture:
    """Resolves when the message's reply is delivered."""

    __slots__ = ("done", "value", "error")

    def __init__(self):
        self.done = False
        self.value = None
        self.error = None

    def result(self):
        if not self.done:
            raise RuntimeError("network reply not yet delivered")
        if self.error is not None:
            raise self.error
        return self.value


class SimNetwork:
    def __init__(self, rng, buggify, clock, min_latency=1, max_latency=6,
                 drop_p=0.002):
        self.rng = rng
        self.buggify = buggify
        self.clock = clock  # () -> current scheduler step
        self.min_latency = min_latency
        self.max_latency = max_latency
        self.drop_p = drop_p
        self._queue = []  # heap [(deliver_at, seq, fn, fut, kind)]
        self._seq = 0
        self._partition_until = 0
        self.delivered = 0
        self.reordered = 0  # messages that overtook an older pending one
        self.dropped = 0
        self.partitions = 0

    def call(self, fn, kind="call"):
        """Send ``fn`` as a message; returns a NetFuture. The thunk runs
        at delivery time — state observed is delivery-time state, exactly
        like a request crossing a real network."""
        now = self.clock()
        fut = NetFuture()
        self._seq += 1
        if self.buggify("net_drop", fire_p=self.drop_p):
            # request (or its reply) lost: the caller learns after a
            # timeout-shaped delay; a lost commit is ambiguous (1021)
            self.dropped += 1
            heapq.heappush(
                self._queue,
                (now + 4 * self.max_latency, self._seq, None, fut, kind),
            )
            return fut
        delay = self.rng.randint(self.min_latency, self.max_latency)
        deliver_at = now + delay
        if deliver_at < self._partition_until:
            # queue behind the partition, jittered for the same reason
            # the heal jitters (see partition())
            deliver_at = self._partition_until + self.rng.randint(
                0, self.max_latency
            )
        heapq.heappush(
            self._queue, (deliver_at, self._seq, fn, fut, kind)
        )
        return fut

    def partition(self, for_steps):
        """Sever the link: every in-flight and new message stalls until
        the partition heals (ref: sim2 network partitions). The heal
        releases the backlog with per-message jitter — clamping all to
        the same instant would tie-break the heap on send order and
        erase the very reordering the latency model created."""
        self.partitions += 1
        until = self.clock() + for_steps
        self._partition_until = max(self._partition_until, until)
        self._queue = [
            (
                d if d >= until
                else until + self.rng.randint(0, self.max_latency),
                s, fn, fut, kind,
            )
            for d, s, fn, fut, kind in self._queue
        ]
        heapq.heapify(self._queue)

    def deliver_due(self, step):
        """Execute every message due at ``step``, in delivery order."""
        while self._queue and self._queue[0][0] <= step:
            _, seq, fn, fut, kind = heapq.heappop(self._queue)
            if any(s < seq for _, s, *_ in self._queue):
                self.reordered += 1  # overtook an older in-flight message
            if fn is None:
                fut.error = err(
                    "commit_unknown_result" if kind == "commit"
                    else "process_behind"
                )
            else:
                try:
                    fut.value = fn()
                except BaseException as e:
                    fut.error = e
            fut.done = True
            self.delivered += 1

    @property
    def pending(self):
        return len(self._queue)
