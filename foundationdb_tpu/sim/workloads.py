"""Simulation workloads + invariant checks.

Ref parity: fdbserver/workloads/ — Cycle.actor.cpp (ring-pointer swaps,
cycle invariant), the ApiCorrectness/Serializability family (randomized
ops vs an oracle), AtomicOps.actor.cpp (counter sums). Each workload is a
generator; every ``yield`` is a scheduling point where the simulation may
interleave other actors or inject faults.
"""

import struct
import zlib

from foundationdb_tpu.core.mutations import Op, apply_atomic

from foundationdb_tpu.core.errors import FDBError


def run_txn(db, fn):
    """Cooperative transactional runner (generator).

    Yields once per attempt; returns (outcome, result, tr) where outcome
    is "committed" or "unknown" (commit_unknown_result) — the caller owns
    1021 disambiguation, like a client.
    """
    tr = db.create_transaction()
    while True:
        yield
        try:
            result = fn(tr)
            tr.commit()
            return ("committed", result, tr)
        except FDBError as e:
            if e.code == 1021:
                return ("unknown", None, tr)
            if not e.is_retryable:
                raise
            tr.reset()


def run_txn_repair(db, fn, stats=None):
    """Repair-aware cooperative runner (txn/repair.py): on a retryable
    conflict it first tries repair — a replayed transaction resubmits
    WITHOUT re-running ``fn`` (re-running would double-apply the
    restored mutations); a cache-seeded one re-runs ``fn`` against the
    verified snapshot. Unrepaired errors reset cold, like ``run_txn``
    (no backoff sleep: the sim scheduler owns time). ``stats`` (when
    given) tallies attempts/conflicts/repairs for the test's asserts.
    """
    tr = db.create_transaction()
    result = None
    while True:
        yield
        try:
            if not tr.repair_ready:
                result = fn(tr)
            fut = tr.commit_async()
            while not fut.done():
                yield  # the scheduler's pump() forms the batch
            tr.commit_finish(fut)
            return ("committed", result, tr)
        except FDBError as e:
            if e.code == 1021:
                return ("unknown", None, tr)
            if not e.is_retryable:
                raise
            if stats is not None:
                stats["conflicts"] = stats.get("conflicts", 0) + 1
            if tr.try_repair(e):
                if stats is not None:
                    stats["repairs"] = stats.get("repairs", 0) + 1
            else:
                tr.reset()


def tpcc_workload(db, n_districts, n_ops, rng, stats, prefix=b"tpcc/",
                  repair=True):
    """New-order-shaped contention (the bench's tpcc client as a sim
    actor): RMW on a hot district counter + an order-row insert keyed
    by the read value + a blind stock update. The value-dependent hot
    read is exactly the shape the repair engine's digest check must
    catch — a stale district counter replayed verbatim would assign a
    duplicate order id. ``repair=False`` runs the same ops through the
    restart-only path for the differential test."""
    dkey = lambda d: prefix + b"district/%03d" % d
    for t in range(n_ops):
        d = rng.randrange(n_districts)
        s = rng.randrange(n_districts * 4)

        def fn(tr, d=d, s=s):
            cur = tr.get(dkey(d))
            oid = int(cur or b"0") + 1
            tr.set(dkey(d), b"%d" % oid)
            tr.set(dkey(d) + b"/order/%08d" % oid, b"o" * 16)
            tr.set(prefix + b"stock/%06d" % s, b"s" * 8)
            return oid

        if repair:
            outcome, _, _tr = yield from run_txn_repair(db, fn, stats)
        else:
            outcome, _, _tr = yield from _run_txn_async(db, fn, stats)
        if outcome == "committed":
            stats["committed"] = stats.get("committed", 0) + 1
            stats.setdefault("per_district", {})
            stats["per_district"][d] = stats["per_district"].get(d, 0) + 1
        else:
            stats["unknown"] = stats.get("unknown", 0) + 1


def _run_txn_async(db, fn, stats=None):
    """The restart-only twin of ``run_txn_repair``: identical async
    commit protocol, cold reset on every retryable error — the
    differential baseline."""
    tr = db.create_transaction()
    while True:
        yield
        try:
            result = fn(tr)
            fut = tr.commit_async()
            while not fut.done():
                yield
            tr.commit_finish(fut)
            return ("committed", result, tr)
        except FDBError as e:
            if e.code == 1021:
                return ("unknown", None, tr)
            if not e.is_retryable:
                raise
            if stats is not None:
                stats["conflicts"] = stats.get("conflicts", 0) + 1
            tr.reset()


def tpcc_check(db, n_districts, stats, prefix=b"tpcc/"):
    """Serializability-equivalence invariant: every district counter
    equals its committed new-order count, and the order rows under it
    are exactly 1..counter (a lost update, double-applied repair, or
    replayed-stale-read would all break the sequence)."""
    per = stats.get("per_district", {})
    assert stats.get("unknown", 0) == 0, "ambiguous outcomes in a " \
        "fault-free differential run"
    for d in range(n_districts):
        key = prefix + b"district/%03d" % d
        row = db.get(key)
        count = int(row) if row is not None else 0
        assert count == per.get(d, 0), (
            f"district {d}: counter {count} != committed {per.get(d, 0)}"
        )
        orders = db.get_range_startswith(key + b"/order/")
        assert len(orders) == count, (
            f"district {d}: {len(orders)} order rows != counter {count}"
        )
        for i, (k, _) in enumerate(orders):
            assert k == key + b"/order/%08d" % (i + 1), (
                f"district {d}: order id gap at {k!r}"
            )


def _enc(i):
    return struct.pack(">I", i)


def _dec(b):
    return struct.unpack(">I", b)[0]


# ───────────────────────────── cycle ────────────────────────────────────
def cycle_setup(db, n_nodes, prefix=b"cycle/"):
    def fn(tr):
        for i in range(n_nodes):
            tr.set(prefix + _enc(i), _enc((i + 1) % n_nodes))

    db.run(fn)


def cycle_workload(db, n_nodes, n_ops, rng, prefix=b"cycle/"):
    """Pointer-rotation transactions: read r→a→b→c, relink to r→b→a→c.
    Every committed state is a single n-cycle, so the invariant is
    insensitive to how commit_unknown_result is disambiguated (the
    reference uses this shape under fault injection for the same
    reason); counter_workload below is the complementary shape whose
    invariant REQUIRES the idempotency-id machinery for exactly-once."""
    key = lambda i: prefix + _enc(i)
    for _ in range(n_ops):
        r = rng.randrange(n_nodes)

        def fn(tr, r=r):
            a = _dec(tr.get(key(r)))
            b = _dec(tr.get(key(a)))
            c = _dec(tr.get(key(b)))
            tr.set(key(r), _enc(b))
            tr.set(key(a), _enc(c))
            tr.set(key(b), _enc(a))

        yield from run_txn(db, fn)


def counter_workload(db, n_ops, stats, key=b"idmp/counter"):
    """Increment-by-one RMW transactions under AUTOMATIC_IDEMPOTENCY
    (ref: the AtomicOps workload shape + IdempotencyId.actor.cpp): the
    counter's final value must equal the increments REPORTED committed —
    the invariant the cycle shape cannot see, because a 1021 retry that
    double-applies still leaves a valid cycle but inflates a counter.
    The runner retries 1021 like a real client (tr.on_error): the id
    machinery — the id row committed atomically with the mutations, the
    client's id-row check, and the proxy's serialized dedupe — makes
    that retry exactly-once. ``stats['committed']`` counts successes."""
    for _ in range(n_ops):
        tr = db.create_transaction()
        tr.options.set_automatic_idempotency()
        while True:
            yield
            try:
                cur = _dec(tr.get(key) or _enc(0))
                tr.set(key, _enc(cur + 1))
                tr.commit()
                stats["committed"] += 1
                break
            except FDBError as e:
                if not e.is_retryable:
                    raise
                stats["retried_1021"] += 1 if e.code == 1021 else 0
                tr.on_error(e)


def slow_cycle_workload(db, n_nodes, n_ops, rng, prefix=b"cycle/"):
    """Cycle txns with yields *between* reads and commit: read versions
    go stale across interleavings and crashes, exercising OCC conflicts
    and recovery fencing on the same invariant."""
    key = lambda i: prefix + _enc(i)
    ops = 0
    while ops < n_ops:
        tr = db.create_transaction()
        try:
            yield
            r = rng.randrange(n_nodes)
            a = _dec(tr.get(key(r)))
            yield
            b = _dec(tr.get(key(a)))
            yield
            c = _dec(tr.get(key(b)))
            tr.set(key(r), _enc(b))
            tr.set(key(a), _enc(c))
            tr.set(key(b), _enc(a))
            yield
            tr.commit()
            ops += 1
        except FDBError as e:
            if e.code == 1021:
                ops += 1  # either way the cycle invariant holds
            elif not e.is_retryable:
                raise
            # retryable: abandon the attempt, new transaction


def batched_cycle_workload(db, n_nodes, n_ops, rng, prefix=b"cycle/"):
    """Cycle txns committed through the *async* path: the actor submits
    to the batching commit proxy and yields until the shared-version
    batch resolves. Many such actors running concurrently are what fills
    the TPU resolver's batch lanes — the live-system analog of the
    reference's commitBatcher accumulating commits from many clients."""
    key = lambda i: prefix + _enc(i)
    ops = 0
    while ops < n_ops:
        tr = db.create_transaction()
        try:
            yield
            r = rng.randrange(n_nodes)
            a = _dec(tr.get(key(r)))
            b = _dec(tr.get(key(a)))
            c = _dec(tr.get(key(b)))
            tr.set(key(r), _enc(b))
            tr.set(key(a), _enc(c))
            tr.set(key(b), _enc(a))
            fut = tr.commit_async()
            while not fut.done():
                yield  # the scheduler's pump() forms the batch
            tr.commit_finish(fut)
            ops += 1
        except FDBError as e:
            if e.code == 1021:
                ops += 1  # either way the cycle invariant holds
            elif not e.is_retryable:
                raise


def cycle_check(db, n_nodes, prefix=b"cycle/"):
    """The walk from node 0 must traverse all nodes and close."""
    rows = dict(db.get_range(prefix, prefix + b"\xff"))
    assert len(rows) == n_nodes, f"expected {n_nodes} nodes, got {len(rows)}"
    seen = set()
    cur = 0
    for _ in range(n_nodes):
        assert cur not in seen, f"cycle broken: revisited {cur}"
        seen.add(cur)
        cur = _dec(rows[prefix + _enc(cur)])
    assert cur == 0, f"walk did not close: ended at {cur}"
    assert len(seen) == n_nodes


# ──────────────────────── serializability ───────────────────────────────
class SerializabilityLog:
    """Shared committed-transaction log for the final linearization check."""

    def __init__(self):
        self.entries = []  # (stamp: 10B versionstamp, reads|None, writes)


def serializability_workload(db, log, actor_id, n_txns, n_keys, rng,
                             prefix=b"ser/"):
    """Random read-modify-write txns, logged with their exact commit
    versionstamp for the end-of-run serial replay.

    Each txn sets a per-actor receipt via SET_VERSIONSTAMPED_VALUE. On
    commit_unknown_result the actor disambiguates by reading its own
    receipt (only it ever writes that key) — and because the receipt
    carries the commit versionstamp, even an ambiguous commit is logged
    at its true position in the serial order. The data write value is a
    function of the token alone so it is reconstructable post-hoc.
    """
    key = lambda i: prefix + b"k%03d" % i
    receipt_key = prefix + b"receipt/%d" % actor_id
    for t in range(n_txns):
        token = b"%d:%d:" % (actor_id, t)
        ks = rng.sample(range(n_keys), 3)
        wval = _enc(zlib.crc32(token))

        def fn(tr, ks=ks, token=token, wval=wval):
            reads = {key(k): tr.get(key(k)) for k in ks}
            tr.set(key(ks[0]), wval)
            # value = token + 10-byte stamp placeholder + LE32 offset trailer
            tr.set_versionstamped_value(
                receipt_key,
                token + b"\x00" * 10 + struct.pack("<I", len(token)),
            )
            return reads

        outcome, reads, tr = yield from run_txn(db, fn)
        writes = {key(ks[0]): wval}
        if outcome == "committed":
            stamp = tr.get_versionstamp()()
            w = dict(writes)
            w[receipt_key] = token + stamp
            log.entries.append((stamp, reads, w))
        else:
            check = yield from run_txn(db, lambda tr: tr.get(receipt_key))
            val = check[1]
            if check[0] == "unknown" or val is None or not val.startswith(token):
                continue  # did not commit (or unknowable)
            stamp = val[len(token):len(token) + 10]
            # committed: the reads were lost with the reply, but the stamp
            # places the writes exactly in the serial order
            w = dict(writes)
            w[receipt_key] = val
            log.entries.append((stamp, None, w))


def serializability_check(db, log, n_keys, prefix=b"ser/"):
    """Replay the committed log in commit-versionstamp order against an
    oracle: every recorded read and the final database state must match —
    strict serializability of the OCC pipeline, checked end to end."""
    key = lambda i: prefix + b"k%03d" % i
    oracle = {}
    for stamp, reads, writes in sorted(log.entries, key=lambda e: e[0]):
        if reads is not None:
            for k, v in reads.items():
                assert oracle.get(k) == v, (
                    f"read {k!r}={v!r} inconsistent with serial replay "
                    f"{oracle.get(k)!r}"
                )
        for k, v in writes.items():
            oracle[k] = v
    final = dict(db.get_range(prefix, prefix + b"\xff"))
    for k, v in oracle.items():
        assert final.get(k) == v, f"final state diverges at {k!r}"
    for k in [key(i) for i in range(n_keys)]:
        assert final.get(k) == oracle.get(k), f"final state diverges at {k!r}"


# ──────────────────────── api correctness ──────────────────────────────
class ApiModel:
    """In-memory model of one actor's keyspace slice (ref: the
    MemoryKeyValueStore ApiCorrectness compares against)."""

    def __init__(self):
        self.data = {}  # committed state

    def snapshot(self):
        return dict(self.data)


def api_correctness_workload(db, model, n_txns, n_keys, rng,
                             prefix=b"api/"):
    """Randomized API transactions checked op-by-op against a model.

    Each transaction interleaves mutations (set / clear / clear_range /
    atomic add) with reads (get, get_range with limit/reverse), and every
    read is asserted against the model's view folded with the txn's own
    staged writes — read-your-writes, range merge, and atomic folding are
    all checked in-flight, then the committed state is folded into the
    model. commit_unknown_result disambiguates via a receipt key the
    actor alone writes. The actor owns ``prefix`` exclusively, so the
    model is exact even under fault injection.
    """
    key = lambda i: prefix + b"k%03d" % i
    receipt_key = prefix + b"receipt"

    for t in range(n_txns):
        token = b"t%d" % t
        script = [rng.randrange(7) for _ in range(rng.randrange(2, 8))]
        cell = {}  # staged view of the most recent attempt (for 1021)

        def fn(tr, script=script, token=token, cell=cell):
            staged = model.snapshot()
            cell["staged"] = staged

            def fold_add(k, param):
                staged[k] = apply_atomic(Op.ADD, staged.get(k), param)

            for op in script:
                if op == 0:  # set
                    k, v = key(rng.randrange(n_keys)), b"v%d" % rng.randrange(999)
                    tr.set(k, v)
                    staged[k] = v
                elif op == 1:  # clear
                    k = key(rng.randrange(n_keys))
                    tr.clear(k)
                    staged.pop(k, None)
                elif op == 2:  # clear_range
                    a, b = sorted(rng.sample(range(n_keys), 2))
                    tr.clear_range(key(a), key(b))
                    for i in range(a, b):
                        staged.pop(key(i), None)
                elif op == 3:  # atomic add
                    k = key(rng.randrange(n_keys))
                    param = struct.pack("<q", rng.randrange(-5, 10))
                    tr.add(k, param)
                    fold_add(k, param)
                elif op == 4:  # get (RYW check)
                    k = key(rng.randrange(n_keys))
                    assert tr.get(k) == staged.get(k), (
                        f"get({k!r}) diverged from model")
                elif op == 5:  # get_range with limit
                    a, b = sorted(rng.sample(range(n_keys + 1), 2))
                    limit = rng.randrange(1, 6)
                    got = tr.get_range(key(a), key(b), limit=limit)
                    want = sorted(
                        (k, v) for k, v in staged.items()
                        if key(a) <= k < key(b)
                    )[:limit]
                    assert got == want, f"get_range diverged: {got} != {want}"
                else:  # reverse range
                    a, b = sorted(rng.sample(range(n_keys + 1), 2))
                    got = tr.get_range(key(a), key(b), reverse=True, limit=3)
                    want = sorted(
                        ((k, v) for k, v in staged.items()
                         if key(a) <= k < key(b)),
                        reverse=True,
                    )[:3]
                    assert got == want, "reverse get_range diverged"
            tr.set(receipt_key, token)
            return staged

        outcome, staged, _tr = yield from run_txn(db, fn)
        if outcome == "unknown":
            check = yield from run_txn(db, lambda tr: tr.get(receipt_key))
            if check[0] == "unknown" or check[1] != token:
                continue  # did not commit; model unchanged
            # a 1021 always comes from the FINAL attempt (run_txn returns
            # on the first one), so the ambiguous-but-committed state is
            # exactly the staged view that attempt recorded
            staged = cell["staged"]
        model.data = {k: v for k, v in staged.items()}
        model.data[receipt_key] = token


def api_correctness_check(db, model, prefix=b"api/"):
    """Final state must equal the model exactly."""
    final = dict(db.get_range(prefix, prefix + b"\xff"))
    assert final == model.data, (
        f"final state diverged: extra={set(final) - set(model.data)} "
        f"missing={set(model.data) - set(final)} "
        f"changed={[k for k in final if k in model.data and final[k] != model.data[k]]}"
    )


# ─────────────────────────── mako load mix ──────────────────────────────
def mako_workload(db, n_txns, n_rows, rng, stats, mix=None, prefix=b"mako/"):
    """Mixed-operation load generator (ref: bindings' mako benchmark
    tool): each transaction performs GRV + a configurable mix of
    get / set / getrange / update (read-modify-write) / clearrange ops
    over a fixed row population. ``stats`` accrues per-op counts; the
    sanity check is that the row population's key set never changes
    (updates overwrite, clears are immediately refilled)."""
    mix = mix or {"get": 4, "set": 2, "getrange": 2, "update": 1, "clearrange": 1}
    ops = [op for op, w in mix.items() for _ in range(w)]
    row = lambda i: prefix + b"r%06d" % i

    for _ in range(n_txns):
        chosen = [rng.choice(ops) for _ in range(rng.randrange(1, 5))]

        def fn(tr, chosen=chosen):
            for op in chosen:
                i = rng.randrange(n_rows)
                if op == "get":
                    tr.get(row(i))
                elif op == "set":
                    tr.set(row(i), b"x" * rng.randrange(8, 32))
                elif op == "getrange":
                    tr.get_range(row(i), row(min(i + 10, n_rows)), limit=10)
                elif op == "update":
                    v = tr.get(row(i)) or b""
                    tr.set(row(i), v[:16] + b"u")
                else:  # clearrange + refill, population invariant kept
                    j = min(i + rng.randrange(1, 4), n_rows)
                    tr.clear_range(row(i), row(j))
                    for k in range(i, j):
                        tr.set(row(k), b"refill")
                stats[op] = stats.get(op, 0) + 1

        outcome, _, _tr = yield from run_txn(db, fn)
        stats["txns"] = stats.get("txns", 0) + 1
        if outcome == "unknown":
            stats["unknown"] = stats.get("unknown", 0) + 1


def mako_check(db, n_rows, prefix=b"mako/"):
    """Row population invariant: exactly n_rows keys, none missing."""
    rows = db.get_range(prefix, prefix + b"\xff")
    assert len(rows) == n_rows, f"population changed: {len(rows)} != {n_rows}"
    for i, (k, _) in enumerate(rows):
        assert k == prefix + b"r%06d" % i


# ───────────────────────────── atomic ops ───────────────────────────────
def atomic_counter_workload(db, actor_id, n_ops, rng, totals,
                            prefix=b"ctr/"):
    """Atomic ADDs with 1021 disambiguation via a receipt; ``totals``
    accrues the definitely-applied sum per counter for the final check."""
    receipt_key = prefix + b"receipt/%d" % actor_id
    for t in range(n_ops):
        c = rng.randrange(4)
        delta = rng.randrange(1, 10)
        token = b"%d:%d" % (actor_id, t)
        ckey = prefix + b"c%d" % c

        def fn(tr, ckey=ckey, delta=delta, token=token):
            tr.add(ckey, struct.pack("<q", delta))
            tr.set(receipt_key, token)

        outcome, _, _tr = yield from run_txn(db, fn)
        if outcome == "unknown":
            check = yield from run_txn(db, lambda tr: tr.get(receipt_key))
            if check[0] == "unknown" or check[1] != token:
                continue
        totals[c] = totals.get(c, 0) + delta


def atomic_counter_check(db, totals, prefix=b"ctr/"):
    for c, expect in totals.items():
        raw = db.get(prefix + b"c%d" % c)
        got = struct.unpack("<q", raw)[0] if raw else 0
        assert got == expect, f"counter {c}: {got} != {expect}"


# ─────────────────── message-level network workloads ────────────────────
def net_exec(net, gen):
    """Drive a thunk-generator over the simulated network: each item the
    generator yields is sent as a message (``(kind, thunk)`` or a bare
    thunk), the actor yields to the scheduler until the reply delivers,
    and the generator resumes with the result. Errors (conflicts, drops,
    fencing) propagate to the caller's retry logic."""
    try:
        item = next(gen)
        while True:
            kind, thunk = (
                item if isinstance(item, tuple) else ("call", item)
            )
            fut = net.call(thunk, kind=kind)
            while not fut.done:
                yield
            item = gen.send(fut.result())
    except StopIteration as s:
        return s.value


def _net_cycle_txn(tr, key, r):
    a = _dec((yield (lambda: tr.get(key(r)))))
    b = _dec((yield (lambda: tr.get(key(a)))))
    c = _dec((yield (lambda: tr.get(key(b)))))

    def relink():
        tr.set(key(r), _enc(b))
        tr.set(key(a), _enc(c))
        tr.set(key(b), _enc(a))

    yield relink
    yield ("commit", tr.commit)


def net_cycle_workload(db, net, n_nodes, n_ops, rng, prefix=b"cycle/"):
    """Cycle transactions where EVERY operation crosses the simulated
    network: reads and commits from concurrent actors reorder against
    each other, stall behind partitions, and drop — the invariant must
    hold anyway (ref: Cycle.actor.cpp under sim2's network)."""
    key = lambda i: prefix + _enc(i)
    ops = 0
    while ops < n_ops:
        tr = db.create_transaction()
        r = rng.randrange(n_nodes)
        try:
            yield from net_exec(net, _net_cycle_txn(tr, key, r))
            ops += 1
        except FDBError as e:
            if e.code == 1021:
                ops += 1  # either way the cycle invariant holds
            elif not e.is_retryable:
                raise


def _one_op(thunk):
    """Single-message transaction body for net_exec."""
    return (yield thunk)


def _net_ser_txn(tr, key, receipt_key, ks, token, wval):
    reads = {}
    for k in ks:
        reads[key(k)] = yield (lambda k=k: tr.get(key(k)))

    def write():
        tr.set(key(ks[0]), wval)
        tr.set_versionstamped_value(
            receipt_key, token + b"\x00" * 10 + struct.pack("<I", len(token))
        )

    yield write
    yield ("commit", tr.commit)
    return reads


def net_serializability_workload(db, net, log, actor_id, n_txns, n_keys,
                                 rng, prefix=b"ser/"):
    """serializability_workload with every read/commit as a reorderable
    network message; 1021 disambiguation via the versionstamped receipt
    also rides the network."""
    key = lambda i: prefix + b"k%03d" % i
    receipt_key = prefix + b"receipt/%d" % actor_id
    for t in range(n_txns):
        token = b"%d:%d:" % (actor_id, t)
        ks = rng.sample(range(n_keys), 3)
        wval = _enc(zlib.crc32(token))
        writes = {key(ks[0]): wval}
        while True:  # retry loop, one attempt per iteration
            tr = db.create_transaction()
            try:
                reads = yield from net_exec(
                    net, _net_ser_txn(tr, key, receipt_key, ks, token, wval)
                )
                stamp = tr.get_versionstamp()()
                w = dict(writes)
                w[receipt_key] = token + stamp
                log.entries.append((stamp, reads, w))
                break
            except FDBError as e:
                if e.code == 1021:
                    # ambiguous: disambiguate via the receipt (only this
                    # actor writes it), itself over the network
                    while True:
                        try:
                            chk = db.create_transaction()
                            val = yield from net_exec(
                                net, _one_op(lambda: chk.get(receipt_key))
                            )
                            break
                        except FDBError as e2:
                            if not e2.is_retryable:
                                raise
                    if val is not None and val.startswith(token):
                        stamp = val[len(token):len(token) + 10]
                        w = dict(writes)
                        w[receipt_key] = val
                        log.entries.append((stamp, None, w))
                    break
                if not e.is_retryable:
                    raise
