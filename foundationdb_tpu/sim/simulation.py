"""Deterministic whole-cluster simulation with fault injection.

Ref parity: fdbrpc/sim2.actor.cpp + fdbserver/SimulatedCluster — the
whole cluster runs in one process under a seeded scheduler; workloads are
cooperative actors interleaved at yield points; BUGGIFY sites inject
faults (spurious commit_unknown_result, dropped batches, GRV rejections,
full crash/recovery); invariants are checked at the end. The same seed
replays the same history, so failures are debuggable.

Workload actors are generators: each ``yield`` is a scheduling point.
Real concurrency hazards (OCC conflicts, retry loops, fencing across
recovery) arise from the interleaving, exactly like the reference's
actor model — cooperative single-thread, adversarial schedule.
"""

import os
import random
import tempfile

from foundationdb_tpu.core import deterministic
from foundationdb_tpu.core.errors import FDBError, err
from foundationdb_tpu.server.cluster import Cluster
from foundationdb_tpu.server.kvstore import open_engine
from foundationdb_tpu.server.tlog import TLogSystem
from foundationdb_tpu.sim.buggify import Buggify
from foundationdb_tpu.sim.network import SimNetwork
from foundationdb_tpu.utils.trace import TraceEvent


class FaultyCommitProxy:
    """Wraps the real commit proxy with BUGGIFY faults at the RPC edge
    (ref: sim2's FlowTransport-level fault injection).

    Injected faults and what they model:
      - commit_applied_then_unknown: reply lost after durability →
        commit_unknown_result with the batch actually committed.
      - commit_dropped: request lost before resolution → the batch is
        NOT committed; clients see commit_unknown_result.
    Both are legal outcomes of 1021 — clients must handle either.
    """

    def __init__(self, inner, buggify):
        self._inner = inner
        self._buggify = buggify

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def commit(self, request):
        if self._buggify("commit_dropped"):
            return err("commit_unknown_result")
        result = self._inner.commit(request)
        if not isinstance(result, FDBError) and self._buggify("commit_applied_then_unknown"):
            return err("commit_unknown_result")
        return result

    def submit(self, request):
        """Async path (BatchingCommitProxy): same two fault sites."""
        if self._buggify("commit_dropped"):
            from foundationdb_tpu.server.batcher import CommitFuture

            fut = CommitFuture()
            fut.set(err("commit_unknown_result"))
            return fut
        fut = self._inner.submit(request)
        if self._buggify("commit_applied_then_unknown"):
            return _UnknownResultFuture(fut)
        return fut


class _UnknownResultFuture:
    """The batch committed (or will), but the reply was lost: the client
    sees commit_unknown_result either way — legal 1021 behavior."""

    def __init__(self, inner):
        self._inner = inner

    def done(self):
        return self._inner.done()

    def result(self, timeout=None):
        self._inner.result(timeout)  # propagate resolution ordering
        return err("commit_unknown_result")


class FaultyGrvProxy:
    def __init__(self, inner, buggify):
        self._inner = inner
        self._buggify = buggify

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def get_read_version(self, priority="default", tags=()):
        # tags passthrough (ride-along fix): without it a TAGGED sim
        # transaction would TypeError here instead of reaching the
        # ratekeeper's per-tag gate
        if self._buggify("grv_rejected"):
            raise err("process_behind")
        return self._inner.get_read_version(priority, tags=tags)


class Simulation:
    # Simulated seconds per scheduling step: the deterministic clock the
    # ratekeeper's token bucket refills from (ref: sim2's g_simulator time
    # advancing at task boundaries, never wall time).
    SIM_DT = 0.001

    def __init__(self, seed=0, buggify=True, crash_p=0.002, n_resolvers=1,
                 datadir=None, engine="memory", machines=0, corrupt_p=0.0,
                 **cluster_kwargs):
        self.seed = seed
        self.engine_kind = engine  # "memory" | "versioned" | "redwood" | "sqlite"
        self.rng = random.Random(seed)
        # silent-corruption fault arming (corrupt_replica): 0 keeps the
        # buggify site cold — existing seeds' fault schedules must not
        # shift — so chaos tests arm it explicitly, like crash_p
        self.corrupt_p = corrupt_p
        # seed the process-wide determinism registry: cluster-visible
        # entropy (proposer ids, directory HCA draws, idempotency ids,
        # cluster-file ids) replays identically for the same seed — the
        # registry is exactly the seam flowlint FL001 enforces
        deterministic.seed(seed)
        self.buggify = Buggify(seed=seed, enabled=buggify)
        self.crash_p = crash_p
        self.n_resolvers = n_resolvers
        # machines > 0 turns on the MACHINE fault model (ref: sim2's
        # machine abstraction): roles are placed onto simulated machines
        # and a reboot kills every co-located role TOGETHER + stalls the
        # network — the correlated-failure shape role-level kills can't
        # produce. 0 = role-level faults only (the historical model).
        self.n_machines = machines
        self.machine_reboots = 0
        self.cluster_kwargs = dict(cluster_kwargs)
        self.cluster_kwargs.setdefault("resolver_backend", "cpu")
        # alternate the commit pack path by seed (NOT an rng draw — that
        # would shift every schedule of existing seeds): half the sim
        # population commits through the flat columnar encode/wire path,
        # half through legacy, so both stay under fault injection. The
        # cpu sim backend resolves legacy either way; the flat half still
        # exercises client encode + the proxy's fallback decision.
        self.cluster_kwargs.setdefault(
            "commit_pack_path", "flat" if seed % 2 == 0 else "legacy"
        )
        self.datadir = datadir or tempfile.mkdtemp(prefix="fdbtpu-sim-")
        os.makedirs(self.datadir, exist_ok=True)
        self.recoveries = 0
        self.steps = 0
        # simulated-time skew consumed by recovery phase marks (the
        # cluster's clock_advance hook): deterministic.now() reads
        # steps*SIM_DT + skew, so phase durations are nonzero, bounded,
        # and identical under a seed — while the ratekeeper and trace
        # clocks stay on the pure step clock, leaving admission and
        # trace output of existing seeds untouched
        self.clock_skew = 0.0
        self.schedule_hash = 0  # order-sensitive digest of scheduling choices
        self._actors = []  # (name, generator)
        # message-level network (ref: sim2): workloads built on
        # net_exec/net_*_workload route every op through it; it survives
        # cluster crashes (infrastructure outlives incarnations) and
        # in-flight messages resolve against the new one via the Database
        self.net = SimNetwork(
            self.rng, self.buggify, clock=lambda: self.steps
        )
        self._build_cluster()
        self.db = self.cluster.database()

    # ───────────────────────── cluster lifecycle ──────────────────────────
    @property
    def _wal_path(self):
        return os.path.join(self.datadir, "wal")

    @property
    def _store_path(self):
        return os.path.join(self.datadir, "store")

    def _build_cluster(self):
        # deterministic traces: events are stamped with the step counter,
        # not wall time, so a seed replays byte-identical trace output
        from foundationdb_tpu.utils.trace import global_trace_log

        global_trace_log().clock = lambda: self.steps
        # the registry's injected clock follows simulated time too, so
        # deterministic.now() readers replay with the schedule
        deterministic.set_clock(
            lambda: self.steps * self.SIM_DT + self.clock_skew
        )
        n_storage = self.cluster_kwargs.get("n_storage", 1)
        self.cluster = Cluster(
            wal_path=self._wal_path,
            storage_engines=[
                open_engine(self.engine_kind, f"{self._store_path}.{i}")
                for i in range(n_storage)
            ],
            n_resolvers=self.n_resolvers,
            # coordinators persist beside the WAL so crash_and_recover
            # exercises the real quorum-locking recovery path
            coordination_dir=self.datadir,
            # admission control ticks on simulated time: same seed, same
            # schedule, same throttling decisions
            rk_clock=lambda: self.steps * self.SIM_DT,
            **self.cluster_kwargs,
        )
        # recovery phase marks consume one simulated tick each: the
        # timeline's per-phase durations come out nonzero and replay
        # byte-identically under a seed
        self.cluster.clock_advance = self._advance_clock
        # the flight recorder's black-box artifacts carry WHICH buggify
        # sites the seed activated (the repro line): hand the cluster a
        # provider. Tests may swap self.buggify for a wrapper fn, so
        # the hookup is best-effort, like the SimBuggifySites event.
        sites = getattr(self.buggify, "activated_sites", None)
        if sites is not None:
            self.cluster.buggify_sites = sites
        self.cluster.commit_proxy = FaultyCommitProxy(
            self.cluster.commit_proxy, self.buggify
        )
        self.cluster.grv_proxy = FaultyGrvProxy(self.cluster.grv_proxy, self.buggify)
        # resolved once per incarnation: the scheduler pumps manual-mode
        # batching every step, and a per-step hasattr through the fault
        # wrapper's __getattr__ would pay an exception per miss
        self._pump = getattr(self.cluster.commit_proxy, "pump", None)

    def _advance_clock(self):
        self.clock_skew += self.SIM_DT

    def crash_and_recover(self):
        """Kill the cluster (losing all volatile state) and restart from
        the engine snapshot + WAL. In-flight transactions keep their old
        read versions and get fenced by the recovered resolver window."""
        if hasattr(self.cluster.commit_proxy, "fail_pending"):
            # queued-but-unbatched commits die with the proxy: clients
            # must see 1021, never hang on an orphaned future
            self.cluster.commit_proxy.fail_pending(
                err("commit_unknown_result")
            )
        self.cluster.commit_proxy.close()
        if self.cluster.regions is not None:
            # the satellite WAL handle must flush before the rebuilt
            # cluster's restored region config truncates and re-seeds it
            self.cluster.regions.close()
        for s in self.cluster.storages:
            s.engine.close()
        self.cluster.tlog.close()
        old_db = self.db
        self._build_cluster()
        # the Database handle survives; transactions resolve the cluster
        # through it, so in-flight txns now talk to the new incarnation
        old_db._cluster = self.cluster
        self.db = old_db
        self.recoveries += 1

    # ─────────────────────────── scheduling ───────────────────────────────
    def add_workload(self, name, gen):
        """gen: a generator object; each ``yield`` is a scheduling point."""
        self._actors.append((name, gen))

    def run(self, max_steps=1_000_000):
        """Interleave all actors to completion under the seeded schedule."""
        live = list(self._actors)
        while live:
            self.steps += 1
            if self.steps > max_steps:
                raise RuntimeError(f"simulation exceeded {max_steps} steps")
            if self.crash_p and self.buggify("cluster_crash", fire_p=self.crash_p):
                self.crash_and_recover()
            self._maybe_fault_roles()
            if self.n_machines:
                self._maybe_reboot_machine()
            if self.net.pending and self.buggify("net_partition", fire_p=0.0015):
                self.net.partition(self.rng.randint(5, 30))
            self.net.deliver_due(self.steps)
            i = self.rng.randrange(len(live))
            self.schedule_hash = (self.schedule_hash * 1000003 + i) & (2**64 - 1)
            name, gen = live[i]
            try:
                next(gen)
            except StopIteration:
                live.pop(i)
            # manual-mode batching: the scheduler is the batch clock
            # (deterministic analog of the proxy's commit interval)
            if self._pump is not None:
                self._pump(self.steps)
            # continuous region streamer: the sim scheduler drives the
            # satellite drain exactly where a thread deployment's
            # daemon loop would — cadence off the injected clock + the
            # "region-stream" deterministic stream, so same-seed runs
            # replicate at the same steps
            reg = self.cluster.regions
            if reg is not None:
                reg.maybe_stream()
            # metrics history: the sim scheduler drives the collector's
            # fixed-cadence windows exactly where a thread deployment's
            # daemon loop would — cadence off the injected clock + the
            # "history-cadence" deterministic stream, so same-seed runs
            # cut identical windows (and the flight recorder dumps
            # identical artifacts)
            self.cluster.history.maybe_collect()
            # continuous consistency scan: the sim scheduler drives the
            # bounded-batch auditor exactly where a thread deployment's
            # daemon loop would — cadence off the injected clock + the
            # "consistency-scan" deterministic stream, so same-seed
            # runs compare identical batches at identical steps
            self.cluster.scanner.maybe_scan()
            # buggify-keyed silent-corruption fault: flip one byte in
            # one replica's engine; the scan must catch it within a
            # round (chaos tests arm the site via corrupt_p)
            if self.corrupt_p and self.buggify(
                "corrupt_replica", fire_p=self.corrupt_p
            ):
                self.corrupt_replica()
        self._actors = []
        # surface WHICH buggify sites this seed activated: a failing
        # seed's repro starts from this line (and a same-seed rerun
        # must print the identical list — activation is seed-keyed).
        # Tests may swap self.buggify for a plain boosting wrapper fn;
        # the activation list is best-effort then, not an attribute err
        sites = getattr(self.buggify, "activated_sites", None)
        TraceEvent("SimBuggifySites").detail(
            seed=self.seed, steps=self.steps,
            activated=",".join(sites()) if sites else "(wrapped)",
        ).log()

    # steps between failure-monitor rounds: kills stay undetected for a
    # window, so clients really do hit (and retry through) dead roles
    MONITOR_EVERY = 7

    def _maybe_fault_roles(self):
        """Role-level fault sites (ref: sim2 killing individual
        processes, not whole clusters):

        - tlog replica kill — never below the ack quorum, so the cluster
          keeps committing on a degraded log tier;
        - storage kill — only when every shard it owns has another live
          owner, so recruitment can re-replicate (a real deployment's
          minimum-replication constraint);
        - resolver kill — any time; recruitment fences the old epoch.

        The failure monitor (cluster.detect_and_recruit) runs every
        MONITOR_EVERY steps; between death and detection clients see
        retryable errors and ride them out.
        """
        c = self.cluster
        tl = c.tlog
        self.role_kills = getattr(self, "role_kills", 0)
        self.tlog_kills = getattr(self, "tlog_kills", 0)
        if isinstance(tl, TLogSystem):
            if tl.live_count > tl.quorum and self.buggify("tlog_kill", fire_p=0.004):
                live = [i for i, l in enumerate(tl.logs) if l.alive]
                tl.kill(self.rng.choice(live))
                self.tlog_kills += 1
            dead = [i for i, l in enumerate(tl.logs) if not l.alive]
            if dead and self.buggify("tlog_revive", fire_p=0.01):
                tl.revive(self.rng.choice(dead))
        if len(c.storages) > 1 and self.buggify("storage_kill", fire_p=0.003):
            victims = [
                sid for sid, s in enumerate(c.storages)
                if s.alive and self._storage_killable(sid)
            ]
            if victims:
                c.storages[self.rng.choice(victims)].kill()
                self.role_kills += 1
        if self.buggify("resolver_kill", fire_p=0.002):
            live = [i for i, r in enumerate(c.resolvers) if r.alive]
            if live:
                c.resolvers[self.rng.choice(live)].kill()
                self.role_kills += 1
        # txn-system kills: a dead sequencer/proxy forces a full
        # recovery generation (resolvers fenced, storage untouched);
        # clients see 1021/1037 until the monitor's next round
        if self.buggify("proxy_kill", fire_p=0.0015):
            target = c._commit_target()
            if target.alive:
                target.kill()
                self.role_kills += 1
        if self.buggify("sequencer_kill", fire_p=0.001):
            if c.sequencer.alive:
                c.sequencer.kill()
                self.role_kills += 1
        if self.steps % self.MONITOR_EVERY == 0:
            events = c.detect_and_recruit()
            if any(role in ("txn-system", "region-failover")
                   for role, _ in events):
                # recovery recruited bare proxies: restore the sim's
                # fault-injection wrappers around the new incarnation
                # (and re-cache the manual-mode pump — the old one
                # would pump a dead batcher, stalling queued commits)
                c.commit_proxy = FaultyCommitProxy(
                    c.commit_proxy, self.buggify
                )
                c.grv_proxy = FaultyGrvProxy(c.grv_proxy, self.buggify)
                self._pump = getattr(c.commit_proxy, "pump", None)

    # ───────────────────── machine fault model ────────────────────────
    # Ref: fdbrpc/sim2.actor.cpp — the simulator models MACHINES hosting
    # several processes; killMachine takes every co-located role down in
    # one event and the machine's network stalls. Placement is offset
    # round-robin so a machine loss pairs DIFFERENT storage/tlog/
    # resolver indices (the correlated shapes a rack failure produces);
    # the txn-system roles (sequencer + commit proxy) live on machine 0.
    def machine_roles(self, mid):
        """(storages, tlog_replicas, resolvers, has_txn_system) hosted
        on machine ``mid`` under the current cluster incarnation."""
        c = self.cluster
        n = self.n_machines
        storages = [sid for sid in range(len(c.storages)) if sid % n == mid]
        tlogs = []
        if isinstance(c.tlog, TLogSystem):
            tlogs = [i for i in range(len(c.tlog.logs))
                     if (i + 1) % n == mid]
        resolvers = [i for i in range(len(c.resolvers)) if i % n == mid]
        return storages, tlogs, resolvers, mid == 0

    def _machine_killable(self, mid):
        """A reboot may not make the cluster unrecoverable: the log must
        keep its ack quorum OUTSIDE the machine, and every shard owned
        by a machine-hosted storage needs a live owner elsewhere (ref:
        sim2's canKillProcesses protection sets)."""
        c = self.cluster
        storages, tlogs, _, _ = self.machine_roles(mid)
        if isinstance(c.tlog, TLogSystem) and tlogs:
            surviving = sum(
                1 for i, log in enumerate(c.tlog.logs)
                if log.alive and i not in tlogs
            )
            if surviving < c.tlog.quorum:
                return False
        for sid in storages:
            if not c.storages[sid].alive:
                continue
            for team in c.dd.map.teams:
                if sid in team and not any(
                    t not in storages and c.storages[t].alive
                    for t in team
                ):
                    return False
        return True

    def reboot_machine(self, mid):
        """Kill every role the machine hosts, in one event, and stall
        the network briefly (its peers see timeouts while it boots).
        Recovery is the ordinary failure-monitor path: storages reboot
        onto their durable engines and replay the log, tlog replicas
        revive, resolvers respawn fenced, and a machine-0 loss forces a
        full txn-system recovery generation."""
        c = self.cluster
        storages, tlogs, resolvers, txn_system = self.machine_roles(mid)
        for sid in storages:
            if c.storages[sid].alive:
                c.storages[sid].kill()
        for i in tlogs:
            if c.tlog.logs[i].alive:
                c.tlog.kill(i)
        for i in resolvers:
            if c.resolvers[i].alive:
                c.resolvers[i].kill()
        if txn_system:
            if c.sequencer.alive:
                c.sequencer.kill()
            target = c._commit_target()
            if target.alive:
                target.kill()
        if self.net.pending:
            self.net.partition(self.rng.randint(3, 12))
        self.machine_reboots += 1
        TraceEvent("SimMachineReboot").detail(
            machine=mid, storages=storages, tlogs=tlogs,
            resolvers=resolvers, txn_system=txn_system).log()

    def kill_primary_region(self):
        """Regional disaster: every primary-region process dies in ONE
        event — the whole storage fleet, every tlog replica, the
        resolvers, and the txn system (ref: sim2 killing an entire
        datacenter). Deliberately ignores the killability protection
        sets: a region loss IS the unrecoverable-locally scenario. The
        failure monitor's next round detects whole-region loss and
        promotes the remote region (Cluster._region_failover); without
        a region config the cluster simply stays down."""
        c = self.cluster
        for s in c.storages:
            if s.alive:
                s.kill()
        if isinstance(c.tlog, TLogSystem):
            for i, log in enumerate(c.tlog.logs):
                if log.alive:
                    c.tlog.kill(i)
        else:
            c.tlog.kill()
        for r in c.resolvers:
            if r.alive:
                r.kill()
        if c.sequencer.alive:
            c.sequencer.kill()
        target = c._commit_target()
        if target.alive:
            target.kill()
        if self.net.pending:
            self.net.partition(self.rng.randint(3, 12))
        TraceEvent("SimRegionKill", severity=30).detail(
            step=self.steps,
            region=(c.regions.config.primary
                    if c.regions is not None else None)).log()

    def corrupt_replica(self):
        """Sim-only silent-corruption fault (ref: sim2's BUGGIFY disk
        corruption): flip one byte of one live key's value in exactly
        ONE replica's engine — below the storage server's overlay, via
        the engine's own write op, so it works on every engine kind
        (memory, sqlite, versioned, redwood) and survives a restart
        like real bit rot would. Only a shard with >=2 live replicas is
        eligible (a lone replica has nothing to diverge from). Returns
        (sid, key) or None if no eligible replica/key exists."""
        c = self.cluster
        smap = c.dd.map
        shard_order = list(range(len(smap)))
        self.rng.shuffle(shard_order)
        for i in shard_order:
            begin, end = smap.shard_range(i)
            end = b"\xff" if end is None or end > b"\xff" else end
            if begin >= end:
                continue  # user keys only: system rows self-heal on replay
            team = [sid for sid in smap.teams[i]
                    if 0 <= sid < len(c.storages) and c.storages[sid].alive]
            if len(team) < 2:
                continue
            sid = team[self.rng.randrange(len(team))]
            eng = c.storages[sid].engine
            rows = [(k, v) for k, v in eng.get_range(begin, end, limit=32)
                    if v]
            if not rows:
                continue
            key, value = rows[self.rng.randrange(len(rows))]
            eng.set(key, bytes([value[0] ^ 0x01]) + value[1:])
            TraceEvent("SimCorruptReplica", severity=30).detail(
                step=self.steps, storage=sid, key=key[:40]).log()
            return sid, key
        return None

    def _maybe_reboot_machine(self):
        if not self.buggify("machine_reboot", fire_p=0.0015):
            return
        victims = [m for m in range(self.n_machines)
                   if self._machine_killable(m)]
        if victims:
            self.reboot_machine(self.rng.choice(victims))

    def _storage_killable(self, sid):
        """Every shard sid owns must keep one other live owner."""
        c = self.cluster
        for team in c.dd.map.teams:
            if sid in team and not any(
                t != sid and c.storages[t].alive for t in team
            ):
                return False
        return True

    def metrics_snapshot(self):
        """The cluster's aggregated metrics section at the current step.
        Under one seed this is BYTE-IDENTICAL across runs: registry
        timestamps come off the sim's step clock and the reservoirs draw
        from the seeded ``metrics-reservoir`` stream (the determinism
        test diffs two same-seed sims' snapshots)."""
        return self.cluster.status()["cluster"]["metrics"]

    def quiesce(self):
        """Flush storage so everything is durable (end-of-run barrier);
        recruit any still-dead roles first so the final checks read a
        healed cluster."""
        self.cluster.detect_and_recruit()
        if hasattr(self.cluster.commit_proxy, "flush"):
            self.cluster.commit_proxy.flush()
        for s in self.cluster.storages:
            s.flush()

    def close(self):
        """Close WAL/engine handles (the datadir itself is left for
        inspection; callers own its lifetime)."""
        self.cluster.commit_proxy.close()
        if self.cluster.regions is not None:
            self.cluster.regions.close()
        for s in self.cluster.storages:
            s.engine.close()
        self.cluster.tlog.close()
        # restore the wall clock: leaving the step clock injected would
        # freeze every LATER (non-sim) cluster's metric spans at this
        # sim's final step (durations measured as now()-now() = 0)
        deterministic.registry().reset_clock()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
