from foundationdb_tpu.sim.buggify import BUGGIFY, Buggify  # noqa: F401
from foundationdb_tpu.sim.simulation import Simulation  # noqa: F401
