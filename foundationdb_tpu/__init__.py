"""foundationdb_tpu — a TPU-native re-design of FoundationDB's capabilities.

A distributed, strictly-serializable, ordered key-value store whose MVCC
conflict detection (the Resolver role; ref: fdbserver/Resolver.actor.cpp,
fdbserver/SkipList.cpp) runs as a batched JAX kernel on TPU.

Public API mirrors the shape of FoundationDB's Python binding
(ref: bindings/python/fdb/__init__.py): ``open()`` returns a Database;
transactions are run with ``db.run(fn)`` / the ``@transactional`` decorator.
"""

from foundationdb_tpu.core.errors import FDBError
from foundationdb_tpu.core.keys import KeyRange, KeySelector, strinc, key_successor
from foundationdb_tpu.core import options

__version__ = "0.1.0"


def open(cluster_file=None, **kw):
    """Open a database and return a Database handle.

    Ref parity: fdb.open() in bindings/python/fdb/__init__.py. With a
    ``cluster_file`` (or an ``address="host:port"`` kwarg) the client
    connects over the RPC transport to an fdbserver-style process
    (tools/fdbserver.py). Without one, the cluster (sequencer, proxies,
    resolver, tlogs, storage) runs in-process with the resolver kernel
    on the default JAX device.
    """
    if cluster_file is not None or "address" in kw:
        import os

        from foundationdb_tpu.rpc.service import RemoteCluster

        # secured clusters (fdbserver --auth-secret) expect the same
        # shared secret from every client; the env var mirrors the
        # server's default so operators configure it once
        kw.setdefault("secret", os.environ.get("FDB_TPU_AUTH_SECRET"))
        if cluster_file is not None:
            remote = RemoteCluster.from_cluster_file(cluster_file, **kw)
        else:
            remote = RemoteCluster(kw.pop("address"), **kw)
        return remote.database()
    from foundationdb_tpu.server.cluster import Cluster

    cluster = Cluster(**kw)
    return cluster.database()


def transactional(func):
    """Decorator: run ``func(tr, ...)`` in a retry loop.

    Ref parity: @fdb.transactional in bindings/python/fdb/impl.py.
    """
    import functools

    @functools.wraps(func)
    def wrapper(db_or_tr, *args, **kwargs):
        from foundationdb_tpu.txn.transaction import Transaction

        if isinstance(db_or_tr, Transaction):
            return func(db_or_tr, *args, **kwargs)
        return db_or_tr.run(lambda tr: func(tr, *args, **kwargs))

    return wrapper
